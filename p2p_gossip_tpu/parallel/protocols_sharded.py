"""Multi-chip random-partner protocols: shard_map push-pull and fanout push.

Scales models/protocols.py the way engine_sharded.py scales the flood
engine: graph rows, seen state, and counters shard along ``nodes``;
independent share chunks along ``shares``. The partner-pick hash
(models/partnersel.py) is a pure function of (global node id, round, pick,
seed), so every shard selects exactly the partners the single-device
engine would — seeded sharded runs are bitwise-identical to seeded
single-device runs, which the tests assert.

Collectives per round, riding ICI:

- the **push** direction scatters rows into arbitrary global partners, so
  each shard scatter-ORs into a global-width buffer and the shards combine
  with an all_to_all "reduce-scatter-OR" (split the buffer by destination
  shard, exchange, OR the received stack) — each device ends with only its
  own rows;
- the state **exchange**: each shard all_gathers its updated local state
  (seen for push-pull, newly-frontier for fanout push) into the global
  history ring that next round's delay-line reads index.

Like the flood engine, the history ring has a ``ring_mode``:

- ``"replicated"`` — full (ring, N, W) ring per chip, write-time
  all_gather, local reads (above);
- ``"sharded"`` — per-chip (ring, N/shards, W). Fanout push reads only
  its OWN rows' past frontiers, so the sharded ring drops the exchange
  all_gather entirely — strictly less ICI traffic AND less HBM. The
  anti-entropy protocols read the PARTNER's past state: the sharded ring
  reconstructs the (t − d) global slice per distinct delay value d at
  read time (one all_gather each; exactly one for the constant-delay
  default) and selects each node's partner row from the slice matching
  its edge delay.

``"auto"`` picks sharded for fanout push always, for anti-entropy under
uniform delay (same traffic, 1/shards HBM), and otherwise replicated
until the ring would exceed RING_REPLICATED_MAX_BYTES per chip.

On the sharded ring, ``exchange="delta"`` replaces the anti-entropy
read-time slice all_gathers with the sparse frontier-delta exchange
(`parallel/exchange.py`). The ring holds cumulative seen-state, so each
tick's delta vs the previous slot is small in steady state; one
all_gather of fixed-capacity (idx, val) buffers moves it (partner picks
are global-random — every shard needs every delta, so all_to_all buys
nothing here), and each shard maintains L per-delay MIRRORS of the
global (t - d) slices, advanced incrementally by OR-ing the received
deltas — exact because seen is OR-monotone. A capacity overflow
anywhere raises the slot's mesh-uniform flag and the affected mirror
advance dense-resets from a full slice all_gather (the hist slot IS the
cumulative slice, so the reset is exact). Bitwise-identical counters on
every path; fanout push's sharded ring reads no remote state at all
("none" — nothing to compress).

``exchange="async"`` (bounded-staleness async ticks,
parallel/async_ticks.py) removes the read-side exchange barrier for the
anti-entropy protocols: partners are global-random — no locality to
preserve — so async(K) is the same protocol with every partner-read
delay clamped host-side to ``max(d, K)`` (`clamp_partner_delays`,
applied by the driver BEFORE staging so the compiled runner, the
checkpoint fingerprint, and the synchronous parity reference all see
the same delays). With every read then >= K ticks deep, the exchange
collective for round t+1's reads can be issued at the END of round t —
a full round before its first reader. The delta path's per-delay
mirrors already ARE that double-buffer (the mirror advance touches only
slots finalized this round or earlier); the dense path grows a
``landed`` carry of prefetched (t - d) global slices that replaces the
read-time per-delay all_gathers, advanced the same way. ``pushk``
pushes same-round digests — there is nothing to overlap — and raises.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from p2p_gossip_tpu.parallel import async_ticks
from p2p_gossip_tpu.parallel.mesh import shard_map

from p2p_gossip_tpu.engine.sync import MIN_CHUNK_SHARES
from p2p_gossip_tpu.models.churn import effective_generated, up_mask_jnp
from p2p_gossip_tpu.models.generation import Schedule
from p2p_gossip_tpu.models.linkloss import drop_mask_jnp
from p2p_gossip_tpu.models.partnersel import pick_index_jnp
from p2p_gossip_tpu.models.topology import Graph
from p2p_gossip_tpu.ops import bitmask
from p2p_gossip_tpu.ops.segment import scatter_or
from p2p_gossip_tpu.parallel.engine_sharded import (
    _padded_churn,
    _padded_device_graph,
)
from p2p_gossip_tpu.parallel.mesh import NODES_AXIS, SHARES_AXIS
from p2p_gossip_tpu import telemetry
from p2p_gossip_tpu.telemetry import digest as tel_digest
from p2p_gossip_tpu.telemetry import rings as tel_rings
from p2p_gossip_tpu.utils.stats import NodeStats


def _reduce_scatter_or(pushed_global: jnp.ndarray, n_shards: int, n_loc: int):
    """(n_padded, W) per-device push buffers -> (n_loc, W) OR of every
    device's pushes into THIS device's rows. all_to_all moves each
    destination shard's slice to its owner; the OR folds the stack."""
    w = pushed_global.shape[-1]
    parts = pushed_global.reshape(n_shards, n_loc, w)
    recv = lax.all_to_all(parts, NODES_AXIS, split_axis=0, concat_axis=0)
    return lax.reduce(recv, jnp.uint32(0), lax.bitwise_or, (0,))


@functools.lru_cache(maxsize=32)
def build_partnered_runner(
    mesh: Mesh,
    protocol: str,            # "pushpull" | "pull" | "pushk"
    n_padded: int,
    ring_size: int,
    chunk_size: int,
    horizon: int,
    fanout: int = 1,
    loss: tuple | None = None,
    record_coverage: bool = False,
    ring_mode: str = "replicated",
    delay_values: tuple | None = None,
    telemetry_on: bool = False,
    exchange_mode: str = "dense",
    delta_capacity: int = 0,
    hub_count: int = 0,
    delta_aggregate: bool = False,
    replica_axis: str | None = None,
    local_replicas: int = 1,
    per_replica_loss: bool = False,
    async_k: int = 0,
    async_staleness: tuple = (),
):
    """Compile the per-pass runner for a random-partner protocol over the
    mesh. Memoized on mesh/shapes like engine_sharded.build_sharded_runner.

    ``replica_axis`` switches to CAMPAIGN mode over a factorized
    (replica, node) mesh, exactly like
    engine_sharded.build_sharded_runner: the round step is vmapped over
    each replica shard's ``local_replicas`` batch inside one shared
    fori_loop. Per-replica operands grow a leading replica dim —
    origins/gen_ticks (R, chunk), churn intervals (R, n_padded, K, still
    replicated over nodes: partner up-checks need every node), the
    protocol seed becomes an (R,) vector, and ``per_replica_loss``
    appends an (R,) uint32 loss-seed vector (static ``loss`` then
    (threshold, None); traced seeds feed the same coin, so solo runs
    with the matching static seed are bitwise-identical). Outputs keep
    the replica axis instead of the share-shard stack; second return
    value is the per-replica pass width (``chunk_size``).

    Counters come back stacked per share-shard — (n_share_shards, n_padded)
    int32 received and uint32 sent lo/hi pairs — and the host folds them in
    int64 (a psum of the raw u64 halves would drop carries).

    ``telemetry_on`` (static) carries a (horizon, NUM_METRICS) metric
    ring through the round loop (rows psum'ed over node shards; one ring
    per share-shard, stacked like the counters) — one extra trailing
    output.

    ``exchange_mode`` "delta" (sharded ring, anti-entropy protocols
    only) swaps the per-delay slice all_gathers for the sparse
    seen-state delta exchange (module docstring): one fixed-capacity
    all_gather of changed-word buffers per round plus L incrementally
    advanced mirrors of the delayed global slices — bitwise-identical
    counters, one extra trailing (1, 8) uint32 counter output
    [used_entries_lo, used_entries_hi, overflow_write_ticks,
    dense_fallback_reads, exchange_ticks, 0, 0, 0] per share-shard.

    ``exchange_mode`` "hub" rides the same machinery with the
    degree-split transport on top: ``hub_count`` top-degree rows per
    shard ship their delta words index-free via a per-round all_gather
    into a slot-aligned hub ring, and the sparse buffers carry only the
    tail cut — the caller appends three operands (``need_tail``
    (n_padded, 1) bool, ``hub_local`` (k, h), ``hub_global`` (k, h))
    after the base eight. The mirror advance overlays the slot's hub
    block onto the scattered tail canvas (disjoint row sets) before the
    OR — bitwise-identical by OR-monotonicity. ``hub_count == 0``
    degenerates to the plain delta program. ``delta_aggregate`` selects
    compress_deltas's destination-major pack (host-side
    `exchange.choose_aggregate` decision; outputs are bitwise-identical
    either way).

    ``async_k`` > 0 (sharded ring, anti-entropy only — the driver feeds
    delays already clamped to >= K via `clamp_partner_delays`) enables
    the bounded-staleness async read side (module docstring): on the
    dense transport a ``landed`` carry of prefetched (t - d) global
    slices replaces the read-time all_gathers, advanced at the end of
    each round from the just-written ring (exact for every d >= 1 —
    slot t + 1 - d is final once round t's write lands); the delta
    mirrors need no restructuring. ``async_staleness`` pairs each
    ``delay_values`` entry with its added-lateness amount
    (`protocol_staleness_amounts` — the builder only sees clamped
    delays, so the pre-clamp bookkeeping must ride in) for the
    ``staleness``/``stale_folds`` telemetry columns."""
    if protocol not in ("pushpull", "pull", "pushk"):
        raise ValueError(f"unknown protocol {protocol!r}")
    if fanout < 1:
        raise ValueError(f"fanout must be >= 1, got {fanout}")
    tel = tel_rings.active(telemetry_on)
    dig = tel_digest.active(telemetry_on)
    campaign = replica_axis is not None
    if campaign:
        if local_replicas < 1:
            raise ValueError(
                f"local_replicas must be >= 1, got {local_replicas}"
            )
        n_share_shards = 1
    else:
        n_share_shards = mesh.shape[SHARES_AXIS]
    if per_replica_loss and (not campaign or loss is None):
        raise ValueError(
            "per_replica_loss requires replica_axis and a loss model"
        )
    rb = local_replicas if campaign else 1
    n_node_shards = mesh.shape[NODES_AXIS]
    n_loc = n_padded // n_node_shards
    w = bitmask.num_words(chunk_size)
    k = fanout if protocol == "pushk" else 1
    # "pushpull" and "pull" share the anti-entropy shape (one partner, ring
    # of seen-states); "pull" skips the push direction and credits `sent`
    # to the responder (see run_pushpull_sim's mode="pull" docs).
    anti = protocol in ("pushpull", "pull")
    sharded_ring = ring_mode == "sharded"
    hist_rows = (n_padded // n_node_shards) if sharded_ring else n_padded
    # "hub" rides the delta machinery: tail rows on the sparse buffers,
    # hub rows on a dense per-round all_gather block (hub_count == 0
    # degenerates to the plain delta program — no zero-size collectives).
    delta = exchange_mode in ("delta", "hub")
    hub = exchange_mode == "hub" and hub_count > 0
    if delta and not (sharded_ring and anti):
        raise ValueError(
            "exchange_mode='delta' needs the sharded ring and an "
            "anti-entropy protocol (fanout push reads no remote state)"
        )
    if delta and delta_capacity < 1:
        raise ValueError(f"delta_capacity must be >= 1, got {delta_capacity}")
    if delta and ring_size < 2:
        # The per-tick delta compares against the previous slot, which
        # must survive this tick's write.
        raise ValueError("exchange_mode='delta' needs ring_size >= 2")
    if delta:
        from p2p_gossip_tpu.parallel import exchange as exch
    if async_k > 0:
        if not (sharded_ring and anti):
            raise ValueError(
                "async_k > 0 needs the sharded ring and an anti-entropy "
                "protocol (fanout push exchanges same-round digests — "
                "nothing to overlap; parallel/async_ticks.py)"
            )
        if not delay_values or len(async_staleness) != len(delay_values):
            raise ValueError(
                "async_k > 0 needs delay_values and a matching "
                "async_staleness tuple (one amount per distinct delay)"
            )
    # Dense transport under async: the landed double-buffer replaces the
    # read-time slice all_gathers (the delta mirrors already are one).
    landed_on = async_k > 0 and not delta
    n_groups = len(delay_values) if delay_values else 1

    def pass_fn(
        ell_idx, ell_delay, degree, churn_start, churn_end,
        origins, gen_ticks, seed, *extra_args,
    ):
        # Local: ell_* (n_loc, dmax), degree (n_loc,), origins/gen_ticks
        # (chunk_size,). Replicated: churn_* (n_padded, K) — partner up
        # checks need every node's intervals — and the seed scalar.
        # Campaign mode prepends a local replica dim rb to churn_*,
        # origins, gen_ticks and the seed, and appends the per-replica
        # loss-seed vector (rb,) when per_replica_loss. The hub split
        # appends (need_tail, hub_local, hub_global) after that.
        base_extra = 1 if (campaign and per_replica_loss) else 0
        lseeds = extra_args[0] if base_extra else None
        if hub:
            need_tail = extra_args[base_extra]          # (n_loc, 1) bool
            hub_rows_l = extra_args[base_extra + 1][0]  # (h,) local rows
            hub_global = extra_args[base_extra + 2]     # (k, h) global
        row_offset = lax.axis_index(NODES_AXIS).astype(jnp.int32) * n_loc
        node_ids = row_offset + jnp.arange(n_loc, dtype=jnp.int32)
        slots = jnp.arange(chunk_size, dtype=jnp.int32)
        rows_l = jnp.arange(n_loc, dtype=jnp.int32)
        live_row = degree > 0  # ELL padding rows never exchange

        state = (
            jnp.zeros((n_loc, w), dtype=jnp.uint32),              # seen
            # History ring: global rows (replicated) or this shard's rows
            # only (sharded — read_slice reassembles what's needed).
            jnp.zeros((ring_size, hist_rows, w), dtype=jnp.uint32),
            jnp.zeros((n_loc,), dtype=jnp.int32),                 # received
            jnp.zeros((n_loc,), dtype=jnp.uint32),                # sent lo
            jnp.zeros((n_loc,), dtype=jnp.uint32),                # sent hi
            jnp.zeros(
                (horizon if record_coverage else 0,
                 chunk_size if record_coverage else 0),
                dtype=jnp.int32,
            ),                                                    # coverage
        )
        if tel:
            state = state + (tel_rings.init(horizon),)            # metrics
        dig_i = 6 + (1 if tel else 0)
        if dig:
            state = state + (tel_digest.init(horizon),)           # digests
        ex_i = 6 + (1 if tel else 0) + (1 if dig else 0)
        if delta:
            # Every shard needs every delta (global-random partners):
            # one buffer per shard, all rows candidates, self included.
            # Under the hub split the dense block ships the hub rows,
            # so the sparse buffers carry only the tail cut.
            need_all = (
                need_tail if hub
                else jnp.ones((n_loc, 1), dtype=jnp.bool_)
            )
            state = state + (
                # Per-delay mirrors of the global (t - d) seen slices —
                # invariant at entry to body(t): mirrors[j] equals the
                # all_gathered hist[(t - delay_values[j]) mod ring].
                jnp.zeros(
                    (len(delay_values), n_padded, w), dtype=jnp.uint32
                ),
                # Received-delta rings, slot-aligned with hist; axis 1
                # is the source shard. idx -1 = empty.
                jnp.full(
                    (ring_size, n_node_shards, delta_capacity),
                    -1, dtype=jnp.int32,
                ),
                jnp.zeros(
                    (ring_size, n_node_shards, delta_capacity),
                    dtype=jnp.uint32,
                ),
                jnp.zeros((ring_size,), dtype=jnp.bool_),  # overflow flags
                # [used_lo, used_hi, overflow_writes, fallback_reads,
                #  exchange_ticks, 0, 0, 0]
                jnp.zeros((8,), dtype=jnp.uint32),
            )
        if hub:
            # Hub delta-word blocks, slot-aligned with didx_ring: every
            # shard's hub-row d_words, all_gathered each round. OR-ing a
            # slot's block into a mirror is exact (deltas are
            # OR-monotone; unwritten slots hold zeros — a no-op).
            state = state + (
                jnp.zeros(
                    (ring_size, n_node_shards * hub_count, w),
                    dtype=jnp.uint32,
                ),
            )
        landed_i = (
            6 + (1 if tel else 0) + (1 if dig else 0)
            + (5 if delta else 0) + (1 if hub else 0)
        )
        if landed_on:
            # Async landed double-buffer: one prefetched global (t - d)
            # seen-slice per distinct delay. Zeros-init is exact — at
            # t=0 every read targets pre-history (all-zero) slices.
            state = state + (
                jnp.zeros(
                    (len(delay_values), n_padded, w), dtype=jnp.uint32
                ),
            )
        if campaign:
            # One state copy per local replica: the round step is
            # vmapped over this leading rb axis inside the fori_loop.
            state = jax.tree_util.tree_map(
                lambda a: jnp.broadcast_to(a, (rb,) + a.shape), state
            )

        def tick(rstate, origins_r, gen_ticks_r, seed_r, lseed_r,
                 churn_start_r, churn_end_r, t):
            # ONE replica's round over its node shard — the solo body
            # verbatim; all collectives address NODES_AXIS only, so the
            # campaign vmap batches them per replica.
            seen, hist, received, sent_lo, sent_hi, cov_hist = rstate[:6]
            if delta:
                (mirrors, didx_ring, dval_ring, dflag_ring,
                 ectr) = rstate[ex_i:ex_i + 5]
                hub_ring = rstate[ex_i + 5] if hub else None
            landed = rstate[landed_i] if landed_on else None
            # The remote views THIS round folds in (pre-advance) — what
            # the staleness telemetry charges against.
            views_in = mirrors if delta else landed  # None unless async
            t = jnp.int32(t)
            if anti:
                kidx = pick_index_jnp(node_ids, t, 0, degree, seed_r)
                partners = ell_idx[rows_l, kidx]          # (n_loc,) global
                delay = ell_delay[rows_l, kidx]
            else:
                picks = jnp.arange(k, dtype=jnp.int32)[None, :]
                kidx = pick_index_jnp(
                    node_ids[:, None], t, picks, degree[:, None], seed_r
                )
                partners = ell_idx[rows_l[:, None], kidx]  # (n_loc, k)
                delay = ell_delay[rows_l[:, None], kidx]

            slot = jnp.mod(t - delay, ring_size)
            if sharded_ring:
                # Own-row reads are local in the sharded layout.
                loc_flat = hist.reshape(ring_size * hist_rows, w)
                if anti:
                    my_old = loc_flat[slot * hist_rows + rows_l]
                    # Partner state: reassemble the (t - d) global slice
                    # per distinct delay value and select each node's
                    # partner row from the slice its edge dictates. The
                    # delta path reads the incrementally-advanced
                    # mirrors instead — no per-delay all_gather.
                    remote = jnp.zeros((n_loc, w), dtype=jnp.uint32)
                    for j, dval in enumerate(delay_values):
                        if delta:
                            f_d = mirrors[j]
                        elif landed_on:
                            # The prefetched slice — its all_gather was
                            # issued at the end of the PREVIOUS round.
                            f_d = landed[j]
                        else:
                            f_d = lax.all_gather(
                                hist[jnp.mod(t - dval, ring_size)],
                                NODES_AXIS, axis=0, tiled=True,
                            )
                        remote = jnp.where(
                            (delay == dval)[:, None], f_d[partners], remote
                        )
                else:
                    my_old = loc_flat[slot * hist_rows + rows_l[:, None]]
            else:
                flat = hist.reshape(ring_size * n_padded, w)
                if anti:
                    remote = flat[slot * n_padded + partners]          # pull
                    my_old = flat[slot * n_padded + node_ids]          # push
                else:
                    my_old = flat[slot * n_padded + node_ids[:, None]]  # (n_loc,k,W)

            up = up_mask_jnp(churn_start_r, churn_end_r, t)   # (n_padded,)
            self_ids = node_ids if anti else node_ids[:, None]
            attempted = (
                up[self_ids] & up[partners]
                & (live_row if anti else live_row[:, None])
            )
            pull_ok = push_ok = attempted
            if loss is not None:
                thr = loss[0]
                lseed = loss[1] if lseed_r is None else lseed_r
                push_ok = attempted & ~drop_mask_jnp(
                    self_ids, partners, t, thr, lseed
                )
                if anti:
                    pull_ok = attempted & ~drop_mask_jnp(
                        partners, node_ids, t, thr, lseed
                    )

            if anti:
                # Responder credit for pull mode, before loss masking.
                pc_remote = bitmask.popcount_rows(remote)
                remote = jnp.where(pull_ok[:, None], remote, jnp.uint32(0))
                if protocol == "pull":
                    pushed_local = jnp.zeros((n_loc, w), dtype=jnp.uint32)
                    # Each attempted pull credits the (possibly remote)
                    # responder; contributions sum across node shards.
                    # uint32 accumulator — the driver guards
                    # degree x chunk < 2^32 (see _check_pull_credit_bound).
                    sent_add = lax.dynamic_slice_in_dim(
                        lax.psum(
                            jnp.zeros((n_padded,), dtype=jnp.uint32)
                            .at[partners]
                            .add(
                                jnp.where(attempted, pc_remote, 0)
                                .astype(jnp.uint32)
                            ),
                            NODES_AXIS,
                        ),
                        row_offset, n_loc,
                    )
                else:
                    pushed = scatter_or(
                        n_padded, partners,
                        jnp.where(push_ok[:, None], my_old, jnp.uint32(0)),
                    )
                    pushed_local = _reduce_scatter_or(
                        pushed, n_node_shards, n_loc
                    )
                    sent_add = jnp.where(
                        attempted, bitmask.popcount_rows(my_old), 0
                    )
            else:
                payload_ok = jnp.where(
                    push_ok[..., None], my_old, jnp.uint32(0)
                )
                pushed = scatter_or(
                    n_padded, partners.reshape(-1),
                    payload_ok.reshape(n_loc * k, w),
                )
                pushed_local = _reduce_scatter_or(pushed, n_node_shards, n_loc)
                pick_cnt = bitmask.popcount_rows(
                    my_old.reshape(n_loc * k, w)
                ).reshape(n_loc, k)
                sent_add = jnp.sum(jnp.where(attempted, pick_cnt, 0), axis=1)

            sent_lo, sent_hi = bitmask.add_u64(sent_lo, sent_hi, sent_add)

            local_origin_rows = origins_r - row_offset
            in_shard = (local_origin_rows >= 0) & (local_origin_rows < n_loc)
            gen_active = (gen_ticks_r == t) & in_shard & up[origins_r]
            gen_bits = bitmask.slot_scatter(
                n_loc, w, local_origin_rows, slots, gen_active
            )

            if anti:
                incoming = (remote | pushed_local) & ~seen
                newly_cnt = bitmask.popcount_rows(incoming)
                if tel:
                    newbits = incoming | (gen_bits & ~seen)
                    gathered = tel_rings.total_bits(remote | pushed_local)
                    if loss is None:
                        dropped = jnp.uint32(0)
                    else:
                        dropped = tel_rings.u32sum(
                            jnp.where(attempted & ~pull_ok, pc_remote, 0)
                        )
                        if protocol != "pull":
                            dropped = dropped + tel_rings.u32sum(
                                jnp.where(
                                    attempted & ~push_ok,
                                    bitmask.popcount_rows(my_old), 0,
                                )
                            )
                received = received + newly_cnt
                seen = seen | incoming | gen_bits
                exchange = seen                       # hist holds seen-state
            else:
                newly = pushed_local & ~seen
                newly_cnt = bitmask.popcount_rows(newly)
                if tel:
                    newbits = newly | (gen_bits & ~seen)
                    gathered = tel_rings.total_bits(pushed_local)
                    dropped = (
                        jnp.uint32(0)
                        if loss is None
                        else tel_rings.u32sum(
                            jnp.where(attempted & ~push_ok, pick_cnt, 0)
                        )
                    )
                received = received + newly_cnt
                seen = seen | newly | gen_bits
                exchange = newly | gen_bits           # hist holds frontier
            if sharded_ring:
                if delta:
                    # The previous slot's cumulative slice — read before
                    # this tick's write (distinct slots: ring_size >= 2).
                    prev = hist[jnp.mod(t - 1, ring_size)]
                # Local write; reads reassemble at read time (or stay
                # local entirely for fanout push).
                hist = hist.at[jnp.mod(t, ring_size)].set(exchange)
            else:
                full = lax.all_gather(exchange, NODES_AXIS, axis=0, tiled=True)
                hist = hist.at[jnp.mod(t, ring_size)].set(full)
            if delta:
                # Write-time sparse exchange: the seen-state is
                # cumulative, so this tick's delta vs the previous slot
                # is exactly the words OR-advancing every mirror needs.
                d_words = exchange & ~prev
                cidx, cval, dcounts = exch.compress_deltas(
                    d_words, need_all, delta_capacity,
                    aggregate=delta_aggregate,
                )
                idx_recv = lax.all_gather(cidx, NODES_AXIS, axis=0, tiled=True)
                val_recv = lax.all_gather(cval, NODES_AXIS, axis=0, tiled=True)
                ovf = lax.psum(
                    jnp.any(dcounts > delta_capacity).astype(jnp.int32),
                    NODES_AXIS,
                ) > 0
                slot_w = jnp.mod(t, ring_size)
                didx_ring = didx_ring.at[slot_w].set(idx_recv)
                dval_ring = dval_ring.at[slot_w].set(val_recv)
                dflag_ring = dflag_ring.at[slot_w].set(ovf)
                if hub:
                    # Index-free hub leg: the hub rows' delta words ride
                    # a plain all_gather into the slot-aligned hub ring.
                    hub_all = lax.all_gather(
                        d_words[hub_rows_l], NODES_AXIS, axis=0, tiled=True
                    )
                    hub_ring = hub_ring.at[slot_w].set(hub_all)
                # Advance each mirror to the slice next round reads:
                # u = t + 1 - d. A flagged slot dense-resets from a full
                # slice all_gather (the hist slot IS the cumulative
                # slice — exact); otherwise OR in the slot's received
                # deltas (an unwritten slot holds -1 indices -> no-op,
                # matching the all-zero pre-history slices).
                new_mirrors = []
                fb_t = jnp.zeros((), dtype=jnp.uint32)
                for j, dv in enumerate(delay_values):
                    slot_u = jnp.mod(t + 1 - dv, ring_size)

                    def dense_m(_, s=slot_u):
                        return lax.all_gather(
                            hist[s], NODES_AXIS, axis=0, tiled=True
                        )

                    def sparse_m(_, s=slot_u, mj=mirrors[j]):
                        recon = exch.scatter_deltas(
                            didx_ring[s], dval_ring[s], n_loc, w, n_padded
                        )
                        if hub:
                            # Overlay the slot's hub block onto the tail
                            # canvas (disjoint rows — the tail plan
                            # excludes hub rows), then OR the combined
                            # delta into the mirror.
                            recon = exch.overlay_hub(
                                recon, hub_global, hub_ring[s]
                            )
                        return mj | recon

                    new_mirrors.append(
                        lax.cond(
                            dflag_ring[slot_u], dense_m, sparse_m,
                            operand=None,
                        )
                    )
                    fb_t = fb_t + dflag_ring[slot_u].astype(jnp.uint32)
                mirrors = jnp.stack(new_mirrors)
                used_t = lax.psum(
                    jnp.sum(jnp.minimum(dcounts, delta_capacity)),
                    NODES_AXIS,
                ).astype(jnp.uint32)
                u_lo, u_hi = bitmask.add_u64(ectr[0], ectr[1], used_t)
                ectr = jnp.stack((
                    u_lo, u_hi,
                    ectr[2] + ovf.astype(jnp.uint32),
                    ectr[3] + fb_t,
                    ectr[4] + jnp.uint32(1),
                    ectr[5], ectr[6], ectr[7],
                ))
            if landed_on:
                # Advance the double-buffer to the slices the NEXT round
                # reads (u = t + 1 - d): one background all_gather per
                # distinct delay, issued a full round before its first
                # reader — the read-side barrier the async mode removes.
                # The post-write ring is exact for every d >= 1: slot u
                # was finalized by this round's write (d = 1) or an
                # earlier one, and no later write touches it before the
                # read (ring_size >= dmax + 1).
                landed = jnp.stack([
                    lax.all_gather(
                        hist[jnp.mod(t + 1 - dv, ring_size)],
                        NODES_AXIS, axis=0, tiled=True,
                    )
                    for dv in delay_values
                ])
            if record_coverage:
                cov = lax.psum(
                    bitmask.coverage_per_slot(seen, chunk_size), NODES_AXIS
                )
                cov_hist = lax.dynamic_update_slice(
                    cov_hist, cov[None], (t, 0)
                )
            out = (seen, hist, received, sent_lo, sent_hi, cov_hist)
            if tel:
                # Per-chip state-slice exchange words received this
                # round (schema docstring; push-direction all_to_all
                # traffic is not included); psum'ed into the mesh total
                # with the rest of the row.
                if delta:
                    ex_words = (
                        jnp.uint32(
                            (n_node_shards - 1)
                            * (2 * delta_capacity
                               + (hub_count * w if hub else 0))
                        )
                        + fb_t * jnp.uint32((n_node_shards - 1) * n_loc * w)
                    )
                elif sharded_ring:
                    ex_words = jnp.uint32(
                        n_groups * (n_node_shards - 1) * n_loc * w
                        if anti else 0
                    )
                else:
                    ex_words = jnp.uint32((n_node_shards - 1) * n_loc * w)
                # Async staleness accounting (schema docstring): each
                # delay bucket folding remote state later than its
                # original delay charges its added lateness whenever the
                # remote (cross-shard) part of the consumed view held
                # any bit. Static zeros on every sync path.
                stale_t = jnp.uint32(0)
                folds_t = jnp.uint32(0)
                if async_k > 0 and any(a > 0 for a in async_staleness):
                    remote_row = (
                        jnp.arange(n_padded, dtype=jnp.int32) // n_loc
                        != lax.axis_index(NODES_AXIS).astype(jnp.int32)
                    )
                    for j, amt in enumerate(async_staleness):
                        if amt <= 0:
                            continue
                        pending = jnp.any(
                            jnp.where(
                                remote_row[:, None], views_in[j],
                                jnp.uint32(0),
                            ) != 0
                        ).astype(jnp.uint32)
                        stale_t = stale_t + jnp.uint32(amt) * pending
                        folds_t = folds_t + pending
                pc_newbits = bitmask.popcount_rows(newbits)
                met_row = lax.psum(
                    tel_rings.row(
                        frontier_bits=tel_rings.u32sum(pc_newbits),
                        frontier_nodes=tel_rings.u32sum(pc_newbits > 0),
                        newly_infected=tel_rings.u32sum(newly_cnt),
                        msgs_gathered=gathered,
                        or_work=tel_rings.u32sum(sent_add),
                        loss_dropped=dropped,
                        exchange_words=ex_words,
                        staleness=stale_t,
                        stale_folds=folds_t,
                    ),
                    NODES_AXIS,
                )
                out = out + (tel_rings.write(rstate[6], t, met_row),)
            if dig:
                # Global node ids keep the salts mesh-shape-invariant; the
                # ELL-pad rows stay all-zero and the sparse fold skips
                # them, so this equals the solo protocol digest.
                dval = tel_digest.tick_digest_sharded(
                    seen, received, sent_lo,
                    node_ids=node_ids, axis_name=NODES_AXIS,
                    sent_hi=sent_hi,
                )
                out = out + (tel_digest.write(rstate[dig_i], t, dval),)
            if delta:
                out = out + (mirrors, didx_ring, dval_ring, dflag_ring, ectr)
            if hub:
                out = out + (hub_ring,)
            if landed_on:
                out = out + (landed,)
            return out

        if campaign:
            def body(t, state):
                if per_replica_loss:
                    return jax.vmap(
                        lambda rs, o, g, sd, ls, cs, ce:
                            tick(rs, o, g, sd, ls, cs, ce, t)
                    )(state, origins, gen_ticks, seed, lseeds,
                      churn_start, churn_end)
                return jax.vmap(
                    lambda rs, o, g, sd, cs, ce:
                        tick(rs, o, g, sd, None, cs, ce, t)
                )(state, origins, gen_ticks, seed, churn_start, churn_end)
        else:
            def body(t, state):
                return tick(state, origins, gen_ticks, seed, None,
                            churn_start, churn_end, t)

        loop_out = lax.fori_loop(0, horizon, body, state)
        received, sent_lo, sent_hi = loop_out[2], loop_out[3], loop_out[4]
        cov_hist = loop_out[5]
        if campaign:
            # Campaign outputs already carry the leading replica axis.
            out = (received, sent_lo, sent_hi, cov_hist)
            if tel:
                out = out + (loop_out[6],)
            if dig:
                out = out + (loop_out[dig_i],)
            if delta:
                out = out + (loop_out[ex_i + 4],)
            return out
        # Stack per share-shard (host folds in int64; psum of u32 halves
        # would drop carries).
        out = (received[None], sent_lo[None], sent_hi[None], cov_hist[None])
        if tel:
            out = out + (loop_out[6][None],)
        if dig:
            out = out + (loop_out[dig_i][None],)
        if delta:
            # Achieved-exchange counters (uniform across node shards).
            out = out + (loop_out[ex_i + 4][None],)
        return out

    if campaign:
        in_specs = (
            P(NODES_AXIS, None),  # ell_idx
            P(NODES_AXIS, None),  # ell_delay
            P(NODES_AXIS),        # degree
            # Churn is per replica but still replicated over nodes
            # (partner up-checks need every node's intervals).
            P(replica_axis, None, None),  # churn_start (R, n_padded, K)
            P(replica_axis, None, None),  # churn_end
            P(replica_axis, None),        # origins (R, chunk)
            P(replica_axis, None),        # gen_ticks
            P(replica_axis),              # seed (R,)
        ) + ((P(replica_axis),) if per_replica_loss else ()) + ((
            P(NODES_AXIS, None),  # need_tail (n_padded, 1)
            P(NODES_AXIS, None),  # hub_local (k, h)
            P(None, None),        # hub_global (k, h) replicated
        ) if hub else ())
        out_specs: tuple = (
            P(replica_axis, NODES_AXIS),
            P(replica_axis, NODES_AXIS),
            P(replica_axis, NODES_AXIS),
            P(replica_axis, None, None),  # coverage (psum'ed over nodes)
        )
        if tel:
            out_specs = out_specs + (P(replica_axis, None, None),)
        if dig:
            out_specs = out_specs + (P(replica_axis, None),)
        if delta:
            out_specs = out_specs + (P(replica_axis, None),)
    else:
        in_specs = (
            P(NODES_AXIS, None),  # ell_idx
            P(NODES_AXIS, None),  # ell_delay
            P(NODES_AXIS),        # degree
            P(),                  # churn_start (replicated: partner checks)
            P(),                  # churn_end
            P(SHARES_AXIS),       # origins
            P(SHARES_AXIS),       # gen_ticks
            P(),                  # seed
        ) + ((
            P(NODES_AXIS, None),  # need_tail (n_padded, 1)
            P(NODES_AXIS, None),  # hub_local (k, h)
            P(None, None),        # hub_global (k, h) replicated
        ) if hub else ())
        out_specs = (
            P(SHARES_AXIS, NODES_AXIS),
            P(SHARES_AXIS, NODES_AXIS),
            P(SHARES_AXIS, NODES_AXIS),
            P(SHARES_AXIS, None, None),  # coverage (psum'ed over nodes)
        ) + (
            ((P(SHARES_AXIS, None, None),) if tel else ())
            + ((P(SHARES_AXIS, None),) if dig else ())
            + ((P(SHARES_AXIS, None),) if delta else ())  # exchange ctrs
        )
    mapped = shard_map(
        pass_fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(mapped), (
        chunk_size if campaign else n_share_shards * chunk_size
    )


# --- staticcheck audit spec (p2p_gossip_tpu/staticcheck/) -----------------

def _audit_spec_partnered_runner(
    protocol: str, telemetry_on: bool = False, exchange: str = "dense",
    campaign: bool = False, async_k: int = 0,
):
    """Stage + build the sharded partnered runner on tiny shapes (same
    mesh policy as the flood audit spec). The u64 ``sent`` counter halves
    come back as (n_share_shards, n_padded) uint32 stacks, so the allowed
    uint32 minor dims include the padded row count alongside the bitmask
    word width. ``exchange`` "delta" audits the sparse seen-delta path
    (sharded ring; both mirror-advance cond branches trace). ``campaign``
    audits the replica-factorized mode on a (replicas, nodes) mesh — the
    jit surface run_sharded_protocol_campaign dispatches. ``async_k``
    > 0 audits the bounded-staleness landed-carry path on the dense
    transport (clamped delays, parallel/async_ticks.py)."""
    from p2p_gossip_tpu.models.topology import erdos_renyi
    from p2p_gossip_tpu.parallel.engine_sharded import (
        _audit_campaign_mesh,
        _audit_mesh,
    )
    from p2p_gossip_tpu.staticcheck.registry import AuditSpec
    from p2p_gossip_tpu.telemetry.schema import NUM_METRICS

    if campaign:
        from p2p_gossip_tpu.parallel.mesh import REPLICAS_AXIS

        mesh = _audit_campaign_mesh()
        local_replicas = 2
        r_batch = mesh.shape[REPLICAS_AXIS] * local_replicas
    else:
        mesh, _ = _audit_mesh()
    n_node_shards = mesh.shape[NODES_AXIS]
    graph = erdos_renyi(16, 0.3, seed=0)
    chunk, horizon = 32, 8
    ell_idx, ell_delays, _, degree, ring, _ = _padded_device_graph(
        graph, None, 1, n_node_shards,
        uniform_placeholder=False, with_mask=False,
    )
    n_padded = ell_idx.shape[0]
    churn_start, churn_end = _padded_churn(None, n_padded, n_node_shards)
    capacity = 0
    hub_args: tuple = ()
    if exchange == "delta":
        from p2p_gossip_tpu.parallel import exchange as exch

        n_loc = n_padded // n_node_shards
        w = bitmask.num_words(chunk)
        capacity = exch.delta_capacity(n_loc, n_loc, w)
        runner, pass_size = build_partnered_runner(
            mesh, protocol, n_padded, ring, chunk, horizon, 1,
            (1 << 20, 7), False, ring_mode="sharded", delay_values=(1,),
            telemetry_on=telemetry_on, exchange_mode="delta",
            delta_capacity=capacity,
            replica_axis=("replicas" if campaign else None),
            local_replicas=(local_replicas if campaign else 1),
        )
    elif exchange == "hub":
        # Forced split — the tiny ER graph has no natural hubs, so the
        # honest planner would pick h = 0 and skip the hub program.
        from p2p_gossip_tpu.parallel import exchange as exch

        n_loc = n_padded // n_node_shards
        w = bitmask.num_words(chunk)
        hplan = exch.plan_partnered_hub_split(
            degree, n_node_shards, n_loc, w, hub_rows=2
        )
        capacity = hplan["capacity"]
        runner, pass_size = build_partnered_runner(
            mesh, protocol, n_padded, ring, chunk, horizon, 1,
            (1 << 20, 7), False, ring_mode="sharded", delay_values=(1,),
            telemetry_on=telemetry_on, exchange_mode="hub",
            delta_capacity=capacity, hub_count=hplan["hub_count"],
            delta_aggregate=True,
        )
        hub_args = (
            hplan["need_tail"], hplan["hub_local"], hplan["hub_global"],
        )
    elif async_k:
        ell_delays = async_ticks.clamp_partner_delays(ell_delays, async_k)
        ring = async_ticks.effective_ring(ring, async_k)
        runner, pass_size = build_partnered_runner(
            mesh, protocol, n_padded, ring, chunk, horizon, 1,
            (1 << 20, 7), False, ring_mode="sharded",
            delay_values=(max(1, async_k),), telemetry_on=telemetry_on,
            async_k=async_k, async_staleness=(max(0, async_k - 1),),
        )
    else:
        runner, pass_size = build_partnered_runner(
            mesh, protocol, n_padded, ring, chunk, horizon,
            2 if protocol == "pushk" else 1,
            (1 << 20, 7), False,
            ring_mode=("sharded" if campaign else "replicated"),
            delay_values=((1,) if campaign else None),
            telemetry_on=telemetry_on,
            replica_axis=("replicas" if campaign else None),
            local_replicas=(local_replicas if campaign else 1),
        )
    if campaign:
        origins = np.zeros((r_batch, pass_size), dtype=np.int32)
        gen_ticks = np.full((r_batch, pass_size), horizon, dtype=np.int32)
        gen_ticks[:, :2] = 0
        churn_start = np.zeros((r_batch, n_padded, 1), dtype=np.int32)
        churn_end = churn_start.copy()
    else:
        origins = np.zeros(pass_size, dtype=np.int32)
        gen_ticks = np.full(pass_size, horizon, dtype=np.int32)
        gen_ticks[:2] = 0
    words: tuple = (bitmask.num_words(chunk), n_padded)
    if telemetry_on:
        # Stacked per-shard digest rings are (1, horizon) uint32 — the
        # horizon is a declared minor width, like NUM_METRICS.
        words = words + (NUM_METRICS, horizon)
    if exchange in ("delta", "hub"):
        # Delta buffers (capacity minor dim) and the (1, 8) counter row.
        words = words + (capacity, 8)
    seed = (
        np.full(r_batch, 42, dtype=np.uint32) if campaign
        else np.uint32(42)
    )
    return AuditSpec(
        fn=runner,
        args=(
            ell_idx, ell_delays, degree, churn_start, churn_end,
            origins, gen_ticks, seed,
        ) + hub_args,
        integer_only=True,
        bitmask_words=words,
    )


from p2p_gossip_tpu.staticcheck.registry import register_entry  # noqa: E402

register_entry(
    "parallel.protocols_sharded.pushpull_runner",
    spec=lambda: _audit_spec_partnered_runner("pushpull"),
)
register_entry(
    "parallel.protocols_sharded.pushk_runner",
    spec=lambda: _audit_spec_partnered_runner("pushk"),
)
register_entry(
    "parallel.protocols_sharded.pushpull_runner[telemetry]",
    spec=lambda: _audit_spec_partnered_runner("pushpull", telemetry_on=True),
)
register_entry(
    "parallel.protocols_sharded.pushk_runner[telemetry]",
    spec=lambda: _audit_spec_partnered_runner("pushk", telemetry_on=True),
)
register_entry(
    "parallel.protocols_sharded.pushpull_runner[delta]",
    spec=lambda: _audit_spec_partnered_runner("pushpull", exchange="delta"),
)
register_entry(
    "parallel.protocols_sharded.pushpull_runner[campaign]",
    spec=lambda: _audit_spec_partnered_runner("pushpull", campaign=True),
)
register_entry(
    "parallel.protocols_sharded.pushpull_runner[async]",
    spec=lambda: _audit_spec_partnered_runner("pushpull", async_k=2),
)
register_entry(
    "parallel.protocols_sharded.pushpull_runner[hub]",
    spec=lambda: _audit_spec_partnered_runner("pushpull", exchange="hub"),
)


def _resolve_partnered_exchange(
    exchange: str,
    protocol: str,
    ring_mode: str,
    ell_delays: np.ndarray,
    ring: int,
    n_padded: int,
    n_node_shards: int,
    w: int,
    degree: np.ndarray,
    k_async: int = 0,
    stale_values: tuple = (),
    stale_amounts: tuple = (),
    hub_rows: int | None = None,
) -> tuple:
    """Shared exchange/ring resolution for the partnered drivers (solo
    and campaign — batch/campaign_sharded.py calls this too): pick the
    ring layout, resolve "auto", plan the delta capacity — and under
    ``exchange="hub"`` the degree split
    (`exchange.plan_partnered_hub_split`; partner picks are
    global-random, so node degree ranks the hub set, and the honest
    cost model usually picks h = 0 unless ``hub_rows`` pins it) — and
    assemble the ``stats.extra['exchange']`` report skeleton.

    Returns ``(ring_mode, ring_bytes, delay_values, exchange, capacity,
    hub_ops, aggregate, delta_on, exchange_extra, async_staleness)``
    where ``hub_ops`` is None or ``(hub_count, need_tail, hub_local,
    hub_global)`` — the builder static plus the three input operands the
    runner dispatch appends after the base eight."""
    from p2p_gossip_tpu.parallel import exchange as exch_mod
    from p2p_gossip_tpu.parallel.engine_sharded import resolve_ring_mode

    if exchange not in ("dense", "delta", "auto", "hub"):
        raise ValueError(f"unknown exchange mode {exchange!r}")
    anti = protocol in ("pushpull", "pull")
    if exchange in ("delta", "hub") and anti:
        # The sparse paths compress the sharded ring's read exchange.
        ring_mode = "sharded"
    distinct = tuple(int(v) for v in np.unique(ell_delays))
    if ring_mode == "auto" and protocol == "pushk":
        # Fanout push reads only its own rows' history: the sharded ring
        # drops the exchange all_gather outright.
        ring_mode = "sharded"
    ring_mode, ring_bytes = resolve_ring_mode(
        ring_mode, distinct[0] if len(distinct) == 1 else None,
        ring, n_padded, n_node_shards, w,
    )
    delay_values = distinct if ring_mode == "sharded" and anti else None
    if exchange == "auto":
        exchange = (
            "delta"
            if anti and ring_mode == "sharded" and n_node_shards > 1
            else "dense"
        )
    delta_on = (
        exchange in ("delta", "hub") and anti and ring_mode == "sharded"
    )
    n_loc = n_padded // n_node_shards
    hub_ops = None
    hub_report = None
    if exchange == "hub" and delta_on:
        hplan = exch_mod.plan_partnered_hub_split(
            degree, n_node_shards, n_loc, w,
            delay_splits=len(delay_values), hub_rows=hub_rows,
        )
        capacity = hplan["capacity"]
        hub_report = hplan["report"]
        if hplan["hub_count"] > 0:
            hub_ops = (
                hplan["hub_count"], hplan["need_tail"],
                hplan["hub_local"], hplan["hub_global"],
            )
        # hub_count == 0 degenerates to plain delta on the full cut.
    elif delta_on:
        # Worst case every local row changes — the anti-entropy delta
        # has no static cut to restrict it (global-random partners).
        capacity = exch_mod.delta_capacity(
            n_loc, n_loc, w, len(delay_values)
        )
    else:
        capacity = 0
    # Host-side default for compress_deltas(aggregate=...): modeled
    # scatter-address words (single destination bin here — the delta
    # rides an all_gather, not an all_to_all).
    aggregate = exch_mod.choose_aggregate(1, capacity) if delta_on else False
    dense_kind = (
        ("dense" if anti else "none")
        if ring_mode == "sharded" else "replicated"
    )
    exchange_extra = {
        "mode": ("hub" if hub_ops else "delta") if delta_on else dense_kind,
        "capacity": capacity,
        "modeled_dense_words_per_tick": (
            exch_mod.modeled_exchange_words_per_tick(
                dense_kind, n_shards=n_node_shards, n_loc=n_loc, w=w,
                delay_splits=len(delay_values) if delay_values else 1,
            )
        ),
    }
    if delta_on:
        exchange_extra["aggregated"] = aggregate
        exchange_extra["modeled_delta_words_per_tick"] = (
            exch_mod.modeled_exchange_words_per_tick(
                "delta", n_shards=n_node_shards, n_loc=n_loc, w=w,
                capacity=capacity,
            )
        )
    if hub_report is not None:
        exchange_extra.update({
            "hub_count": hub_report["hub_count"],
            "hub_rows_forced": hub_report["hub_rows_forced"],
            "crossover_h": hub_report["crossover_h"],
            "modeled_hub_words_per_tick": (
                hub_report["modeled_hub_words_per_tick"]
            ),
            "modeled_delta_words_per_tick": (
                hub_report["modeled_delta_words_per_tick"]
            ),
        })
    if k_async:
        exchange_extra.update(async_ticks.modeled_overlap_report(
            ("hub" if hub_ops else "delta") if delta_on else "dense",
            delay_values, k_async, n_node_shards, n_loc, w, capacity,
            hub_count=hub_ops[0] if hub_ops else 0,
        ))
        # group_offsets sees only clamped delays (amounts all 0 there);
        # the real added-lateness bookkeeping is pre-clamp.
        exchange_extra["staleness_amounts"] = list(stale_amounts)
    amounts_by_value = dict(zip(stale_values, stale_amounts))
    async_staleness = (
        tuple(amounts_by_value.get(v, 0) for v in delay_values)
        if k_async else ()
    )
    return (
        ring_mode, ring_bytes, delay_values, exchange, capacity,
        hub_ops, aggregate, delta_on, exchange_extra, async_staleness,
    )


def run_sharded_partnered_sim(
    graph: Graph,
    schedule: Schedule,
    horizon_ticks: int,
    mesh: Mesh,
    protocol: str = "pushpull",
    fanout: int = 2,
    ell_delays: np.ndarray | None = None,
    constant_delay: int = 1,
    chunk_size: int = 4096,
    seed: int = 0,
    churn=None,
    loss=None,
    record_coverage: bool = False,
    checkpoint_path: str | None = None,
    checkpoint_every: int = 1,
    stop_after_chunks: int | None = None,
    ring_mode: str = "auto",
    exchange: str = "dense",
    async_k: int = 2,
    hub_rows: int | None = None,
):
    """Drop-in counterpart of run_pushpull_sim / run_pushk_sim on a device
    mesh: identical per-node counters for any mesh shape (the counter-based
    partner hash keys on global node ids, so shard boundaries change
    nothing), including under churn and link loss.

    ``chunk_size`` is per share-shard, as in run_sharded_sim. With
    ``record_coverage`` also returns the (horizon, num_shares) per-tick
    node-coverage history (psum'ed over node shards, identical values to
    the single-device engines); returns stats alone otherwise, matching
    run_sharded_sim. ``checkpoint_path``/``checkpoint_every``/
    ``stop_after_chunks`` give run_sharded_sim's pass-boundary
    checkpoint/resume contract (mesh shape is fingerprinted — a resume on
    a different mesh starts fresh; not combinable with
    ``record_coverage``).

    ``exchange`` selects the anti-entropy cross-shard state exchange:
    "dense" (per-delay slice all_gathers, the default), "delta" (sparse
    seen-delta buffers + mirrors, module docstring — forces the sharded
    ring, bitwise-identical counters), "auto" (delta whenever the
    anti-entropy ring is sharded across >1 node shards), or "hub" (the
    degree-split transport: the ``hub_rows``-or-planned top-degree rows
    per shard ship their deltas index-free via a per-round all_gather
    block while the sparse buffers carry only the tail —
    `exchange.plan_partnered_hub_split`; the honest cost model usually
    picks h = 0 here, so ``hub_rows`` pins the split for parity tests).
    Fanout push reads no remote state on the sharded ring, so "delta"
    and "hub" degrade to that free path. Resolved mode, modeled
    traffic, and achieved counters land in
    ``stats.extra['exchange']``.

    "async" / "async-dense" / "async-delta" switch the anti-entropy
    read side to the bounded-staleness async path with ``async_k`` = K
    (module and `parallel/async_ticks.py` docstrings): every partner
    read delay is clamped host-side to ``max(d, K)``
    (`clamp_partner_delays` — the exact parity reference is the same
    runner on the pre-clamped delay array), the ring grows to
    ``max(dmax, K) + 1`` slots, and the exchange collectives are issued
    a round ahead of their readers. ``async_k`` is ignored on the
    synchronous spellings. ``pushk`` raises — fanout push exchanges
    same-round digests, nothing to overlap.
    """
    if protocol not in ("pushpull", "pull", "pushk"):
        raise ValueError(f"unknown protocol {protocol!r}")
    if protocol == "pull":
        from p2p_gossip_tpu.models.protocols import _check_pull_credit_bound

        _check_pull_credit_bound(graph, chunk_size, schedule)
    transport, k_async = async_ticks.parse_exchange(exchange, async_k)
    exchange = transport
    if k_async:
        if protocol == "pushk":
            raise ValueError(
                "async exchange needs an anti-entropy protocol "
                "(pushpull/pull): fanout push exchanges same-round "
                "digests — there is nothing to overlap"
            )
        ring_mode = "sharded"
    n_node_shards = mesh.shape[NODES_AXIS]
    chunk_size = min(chunk_size, max(MIN_CHUNK_SHARES, schedule.num_shares))
    chunk_size = bitmask.num_words(chunk_size) * bitmask.WORD_BITS

    # Shared staging with the flood engine; partner picks index per-edge
    # delays (no placeholder) and always land on valid entries (no mask).
    ell_idx, ell_delays, _, degree, ring, _ = _padded_device_graph(
        graph, ell_delays, constant_delay, n_node_shards,
        uniform_placeholder=False, with_mask=False,
    )
    n_padded = ell_idx.shape[0]
    churn_start, churn_end = _padded_churn(churn, n_padded, n_node_shards)
    if k_async:
        # The async clamp happens BEFORE everything downstream — the
        # distinct-delay set, the ring size, the compiled runner, and
        # the checkpoint fingerprint all see the clamped array, so the
        # synchronous run on the same clamped delays is the bitwise
        # parity reference.
        stale_values, stale_amounts = async_ticks.protocol_staleness_amounts(
            ell_delays, k_async
        )
        ell_delays = async_ticks.clamp_partner_delays(ell_delays, k_async)
        ring = async_ticks.effective_ring(ring, k_async)
    else:
        stale_values, stale_amounts = (), ()

    # Ring layout (module docstring). The distinct-delay set comes from
    # the padded ELL delay array — a superset of the valid entries (row
    # padding fills with 1), which costs at most one spare slice
    # all_gather per round and can never miss a real delay.
    w = bitmask.num_words(chunk_size)
    (ring_mode, ring_bytes, delay_values, exchange, capacity, hub_ops,
     aggregate, delta_on, exchange_extra, async_staleness) = (
        _resolve_partnered_exchange(
            exchange, protocol, ring_mode, ell_delays, ring, n_padded,
            n_node_shards, w, degree, k_async, stale_values,
            stale_amounts, hub_rows,
        )
    )
    n_loc = n_padded // n_node_shards

    tel = telemetry.rings_enabled()
    runner, pass_size = build_partnered_runner(
        mesh, protocol, n_padded, ring, chunk_size, horizon_ticks,
        fanout if protocol == "pushk" else 1,
        loss.static_cfg if loss is not None else None,
        record_coverage,
        ring_mode=ring_mode, delay_values=delay_values, telemetry_on=tel,
        exchange_mode=exchange if delta_on else "dense",
        delta_capacity=capacity,
        hub_count=hub_ops[0] if hub_ops else 0,
        delta_aggregate=aggregate,
        async_k=k_async, async_staleness=async_staleness,
    )
    seed_arr = np.uint32(seed & 0xFFFFFFFF)
    n_share_shards = mesh.shape[SHARES_AXIS]

    received = np.zeros(n_padded, dtype=np.int64)
    sent = np.zeros(n_padded, dtype=np.int64)

    from p2p_gossip_tpu.utils.checkpoint import (
        checkpointed_chunks,
        make_checkpointer,
    )

    checkpointer = make_checkpointer(
        checkpoint_path, checkpoint_every, record_coverage,
        lambda: (
            "sharded_partnered_sim", protocol,
            fanout if protocol == "pushk" else 1,
            graph.n, graph.edges(), schedule.origins, schedule.gen_ticks,
            horizon_ticks, chunk_size,
            mesh.shape[SHARES_AXIS], mesh.shape[NODES_AXIS],
            ell_delays, int(seed) & 0xFFFFFFFF,
            churn.down_start if churn is not None else None,
            churn.down_end if churn is not None else None,
            np.asarray(loss.static_cfg, dtype=np.int64)
            if loss is not None
            else None,
        ),
        {"received": received, "sent": sent},
    )

    cov_chunks = []
    exch_counters = np.zeros(3, dtype=np.int64)  # used, ovf, fallback
    exch_ticks = 0
    chunks = schedule.chunk(pass_size) or [schedule]
    for ci, chunk in checkpointed_chunks(chunks, checkpointer, stop_after_chunks):
        origins, gen_ticks = chunk.padded(pass_size, horizon_ticks)
        with telemetry.span(
            "dispatch",
            kernel=f"parallel.protocols_sharded.{protocol}_runner", chunk=ci,
        ):
            args = (
                ell_idx, ell_delays, degree, churn_start, churn_end,
                origins, gen_ticks, seed_arr,
            )
            if hub_ops:
                args = args + (hub_ops[1], hub_ops[2], hub_ops[3])
            out = runner(*args)
        digest_head = None
        if delta_on:
            ec = np.asarray(out[-1], dtype=np.uint64)  # (shards, 8)
            exch_counters[0] += int(
                bitmask.combine_u64(ec[:, 0], ec[:, 1]).sum()
            )
            exch_counters[1] += int(ec[:, 2].sum())
            exch_counters[2] += int(ec[:, 3].sum())
            exch_ticks += int(ec[:, 4].sum())
        if tel:
            r, s_lo, s_hi, cov, met, dstream = out[:6]
            met_np = np.asarray(met)
            dig_np = np.asarray(dstream)
            for k in range(n_share_shards):
                tel_rings.emit_ring(
                    f"parallel.protocols_sharded.{protocol}_runner",
                    met_np[k], t0=0, ticks=horizon_ticks, chunk=ci, shard=k,
                )
                tel_digest.emit_digest(
                    f"parallel.protocols_sharded.{protocol}_runner",
                    dig_np[k], t0=0, ticks=horizon_ticks, chunk=ci, shard=k,
                )
            digest_head = int(dig_np[0][-1])
        else:
            r, s_lo, s_hi, cov = out[:4]
        telemetry.emit_progress(
            f"parallel.protocols_sharded.{protocol}_runner",
            chunk=ci, chunks_total=len(chunks),
            ticks_done=horizon_ticks * (ci + 1), digest_head=digest_head,
        )
        received += np.asarray(r, dtype=np.int64).sum(axis=0)
        sent += bitmask.combine_u64(
            jnp.asarray(s_lo), jnp.asarray(s_hi)
        ).reshape(-1, n_padded).sum(axis=0)
        if record_coverage:
            # Reassemble global slot order: shard k's local slots are the
            # pass's global slots [k*chunk_size, (k+1)*chunk_size).
            cov = np.asarray(cov)  # (n_share_shards, horizon, chunk_size)
            parts = []
            for k in range(n_share_shards):
                live = min(
                    max(chunk.num_shares - k * chunk_size, 0), chunk_size
                )
                parts.append(cov[k, :, :live])
            cov_chunks.append(np.concatenate(parts, axis=1))

    received = received[: graph.n]
    sent = sent[: graph.n]
    generated = effective_generated(schedule, horizon_ticks, churn)
    stats = NodeStats(
        generated=generated,
        received=received,
        forwarded=received.copy(),
        sent=sent,
        processed=generated + received,
        degree=graph.degree.astype(np.int64),
    )
    stats.extra["ring"] = {
        "mode": ring_mode,
        "bytes_per_chip": ring_bytes,
        "slots": ring,
        "delay_splits": len(delay_values) if delay_values else 1,
    }
    if delta_on:
        from p2p_gossip_tpu.parallel.engine_sharded import (
            _achieved_exchange_report,
        )

        exchange_extra = _achieved_exchange_report(
            exchange_extra, exch_counters, exch_ticks,
            n_node_shards, n_loc, w, capacity,
            hub_count=hub_ops[0] if hub_ops else 0,
        )
    stats.extra["exchange"] = exchange_extra
    if record_coverage:
        return stats, np.concatenate(cov_chunks, axis=1)
    return stats
