"""Multi-chip synchronous engine: shard_map over a (shares, nodes) mesh.

Scales the tick engine (engine/sync.py) the way the BASELINE.json headline
config demands (1M nodes over a v5e-8 mesh): graph rows, seen-bitmask, and
counters are sharded along ``nodes``; independent share chunks along
``shares``. Counters `psum` over the shares axis once per pass.

The delay-line history ring has two layouts (``ring_mode``):

- ``"replicated"`` — each chip holds the full (ring, N, W) ring; per tick
  the local newly-frontier is `all_gather`ed over the nodes axis and
  written globally, and the gather-OR reads are purely local. Fastest
  when the ring fits in HBM.
- ``"sharded"`` — each chip holds only ITS rows' history (ring, N/shards,
  W); per-chip ring memory scales down with the mesh. Per-edge delays are
  static host data, so the read side becomes one `all_gather` of the
  (t - d)-slice per distinct delay value d (`ops.ell.split_ell_by_delay`
  plans the per-delay ELLs): for the reference's uniform-latency model
  that is exactly ONE all_gather per tick — the same ICI traffic as
  replicated mode with 1/n_shards the ring HBM — and for an L-valued
  delay distribution it is L all_gathers (traffic xL, the price of
  fitting 1M-node lognormal rings on 16 GB chips).

``"auto"`` picks sharded for uniform delays (strictly better) and
otherwise switches to sharded when the replicated ring would exceed
``RING_REPLICATED_MAX_BYTES`` per chip.

On top of the sharded ring, ``exchange="delta"`` swaps the per-delay
slice all_gathers for the sparse frontier-delta exchange
(`parallel/exchange.py`): one fixed-capacity all_to_all of changed-word
(idx, val) pairs per tick — traffic scales with the frontier delta
instead of N — with a mesh-uniform dense fallback per overflowed ring
slot. Bitwise-identical counters on every path; modeled and achieved
wire words are reported in ``stats.extra['exchange']``.

``exchange="async"`` (bounded-staleness async ticks,
parallel/async_ticks.py) removes the read-side exchange barrier on
either transport: each shard carries a ``landed`` double-buffer — the
completed gather of an older ring slot, issued a full tick before its
first reader — and runs up to K ticks ahead on locally-known bits while
the next gather completes in the background. Results are bitwise
identical, per tick, to the synchronous engine run with cross-shard
edge delays clamped to ``max(d, K)`` (intra-shard edges stay timely);
K=1 is the synchronous program itself. See the async_ticks module
docstring for the exact-semantics contract and the OR-monotonicity
safety argument.

Single-device equivalence is bitwise for BOTH layouts: the tick body ORs
the same edge set in either decomposition, and the tests assert identical
per-node counters against `engine.sync` and `engine.event` across mesh
shapes and ring modes.
"""

from __future__ import annotations

import functools
import os
import sys

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from p2p_gossip_tpu.parallel.mesh import shard_map

from p2p_gossip_tpu.engine.sync import (
    apply_tick_updates,
    assemble_snapshots,
    filter_snapshot_boundaries,
)
from p2p_gossip_tpu.models.churn import effective_generated, up_mask_jnp
from p2p_gossip_tpu.models.generation import Schedule
from p2p_gossip_tpu.models.topology import Graph
from p2p_gossip_tpu.ops import bitmask
from p2p_gossip_tpu.ops.ell import (
    DEFAULT_DEGREE_BLOCK,
    detect_uniform_delay,
    gather_or_frontier,
    shard_bucket_ell,
    split_ell_by_delay,
    tuned_degree_block,
)
from p2p_gossip_tpu.parallel import async_ticks
from p2p_gossip_tpu.parallel.mesh import NODES_AXIS, SHARES_AXIS, pad_to_multiple
from p2p_gossip_tpu import telemetry
from p2p_gossip_tpu.telemetry import digest as tel_digest
from p2p_gossip_tpu.telemetry import rings as tel_rings
from p2p_gossip_tpu.utils.stats import NodeStats


def _rss_log(tag: str) -> None:
    """Staging-memory audit line, enabled by P2P_STAGE_RSS=1: current and
    peak process RSS at each staging milestone. Exists because the 1M
    scale-free virtual-mesh rehearsal OOM-killed a 125 GB host twice
    with no visible culprit — one run under this flag localizes which
    staging step owns the peak instead of guessing from models."""
    if os.environ.get("P2P_STAGE_RSS") != "1":
        return
    import resource

    peak_gb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    try:
        with open("/proc/self/statm") as f:
            cur_gb = (
                int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE") / 1e9
            )
    except (OSError, ValueError, IndexError):
        cur_gb = float("nan")
    print(
        f"[stage-rss] {tag}: cur {cur_gb:.1f} GB, peak {peak_gb:.1f} GB",
        file=sys.stderr, flush=True,
    )


def _padded_device_graph(
    graph: Graph,
    ell_delays: np.ndarray | None,
    constant_delay: int,
    n_node_shards: int,
    uniform_placeholder: bool = True,
    with_mask: bool = True,
):
    """ELL arrays padded so rows divide evenly across node shards. Padding
    rows have empty masks: they never receive or send.

    ``uniform_placeholder`` stages a one-column placeholder delay array
    when every edge shares one delay (the flood engine's fast path never
    reads per-edge delays); the partnered protocols index delays per
    random pick, so they pass False to keep the real array — and also
    ``with_mask=False``, since picks always land on valid ELL entries:
    both the uniform-delay scan and the (N, dmax) mask copy are skipped
    (the mask slot returns None)."""
    _rss_log("padded_device_graph enter")
    ell_idx, ell_mask = graph.ell()
    _rss_log("global ELL materialized")
    if ell_delays is None:
        ell_delays = np.full(ell_idx.shape, constant_delay, dtype=np.int32)
    ell_idx = pad_to_multiple(ell_idx, n_node_shards)
    uniform = (
        detect_uniform_delay(ell_delays, ell_mask)
        if uniform_placeholder
        else None
    )
    _rss_log("uniform-delay detect done")
    ell_mask = pad_to_multiple(ell_mask, n_node_shards) if with_mask else None
    ring = (int(ell_delays.max()) if ell_delays.size else 1) + 1
    if uniform is not None:
        # The uniform fast path never reads per-edge delays: stage one
        # placeholder row per shard instead of (N, dmax) of dead HBM.
        ell_delays = np.ones((ell_idx.shape[0], 1), dtype=np.int32)
    else:
        ell_delays = pad_to_multiple(ell_delays, n_node_shards, fill=1)
    degree = pad_to_multiple(graph.degree.astype(np.int32), n_node_shards)
    return ell_idx, ell_delays, ell_mask, degree, ring, uniform


def _padded_churn(churn, n_padded: int, n_node_shards: int):
    """Churn intervals padded with their node rows ((n_padded, 1) zeros —
    vacuously up — when churn is off)."""
    if churn is not None:
        return (
            pad_to_multiple(churn.down_start, n_node_shards),
            pad_to_multiple(churn.down_end, n_node_shards),
        )
    return (
        np.zeros((n_padded, 1), dtype=np.int32),
        np.zeros((n_padded, 1), dtype=np.int32),
    )


#: Per-chip ceiling for the replicated (ring, N, W) history under
#: ring_mode="auto": above this the sharded ring layout is chosen. The
#: v5e has 16 GB HBM; 1 GiB of replicated ring leaves the rest for ELL,
#: seen, and the gather intermediates.
RING_REPLICATED_MAX_BYTES = 1 << 30


def resolve_ring_mode(
    ring_mode: str,
    uniform: int | None,
    ring: int,
    n_padded: int,
    n_node_shards: int,
    w: int,
) -> tuple[str, int]:
    """Resolve "auto" and return (mode, per-chip ring bytes).

    Uniform delays always take the sharded ring (same ICI traffic, 1/shards
    the HBM); per-edge delays stay replicated until the replicated ring
    would exceed RING_REPLICATED_MAX_BYTES per chip (the sharded read side
    costs one all_gather per distinct delay value per tick)."""
    if ring_mode not in ("auto", "replicated", "sharded"):
        raise ValueError(f"unknown ring_mode {ring_mode!r}")
    replicated_bytes = 4 * ring * n_padded * w
    if ring_mode == "auto":
        if uniform is not None or replicated_bytes > RING_REPLICATED_MAX_BYTES:
            ring_mode = "sharded"
        else:
            ring_mode = "replicated"
    bytes_per_chip = (
        replicated_bytes
        if ring_mode == "replicated"
        else 4 * ring * (n_padded // n_node_shards) * w
    )
    return ring_mode, bytes_per_chip


def _resolve_and_stage_ring(
    ring_mode: str,
    uniform: int | None,
    ring: int,
    n_padded: int,
    n_node_shards: int,
    w: int,
    ell_idx: np.ndarray,
    ell_delay: np.ndarray,
    ell_mask: np.ndarray,
    block: int = DEFAULT_DEGREE_BLOCK,
    bucket_min_rows: int = 2048,
    exchange: str = "dense",
    hub_rows: int | None = None,
    aux_cache: tuple | None = None,
):
    """Resolve the ring layout and stage its operands in one step — the
    shared stanza of both sharded entry points. Returns (ring_mode,
    ell_args, delay_values, bucket_counts, ring_extra, exchange_plan)
    where ``ring_extra`` is the ``stats.extra['ring']`` report dict,
    ``bucket_counts`` is the static per-group bucket layout the runner
    unflattens ``ell_args`` by, and ``exchange_plan`` is the resolved
    frontier-exchange path:
    ``(mode, need, capacity, extra, hub_ops, aggregate)`` — mode
    "dense" (slice all_gathers), "delta" (sparse frontier-delta buffers
    over the cached cut structure, parallel/exchange.py), or "hub"
    (degree-split hub/tail transport: `exchange.plan_hub_split`), with
    ``need`` the (n_padded, n_shards) cut membership to stage (hub rows
    cleared under "hub"), ``hub_ops`` None or the
    ``(hub_count, hub_local, hub_global)`` operand triple, ``aggregate``
    the host-chosen `compress_deltas` packing (`choose_aggregate`), and
    ``extra`` the ``stats.extra['exchange']`` report dict. ``hub_rows``
    pins the hub size (tests; graphs where the cost search picks 0) and
    ``aux_cache`` is `exchange.cached_flood_plan`'s (path, fp, key)
    persistence triple for the cut structure."""
    if exchange not in ("dense", "delta", "auto", "hub"):
        raise ValueError(f"unknown exchange mode {exchange!r}")
    if exchange in ("delta", "hub"):
        # The delta/hub paths compress the sharded ring's write slices;
        # a replicated ring has no read-time exchange to compress.
        ring_mode = "sharded"
    ring_mode, ring_bytes = resolve_ring_mode(
        ring_mode, uniform, ring, n_padded, n_node_shards, w
    )
    if exchange == "auto":
        exchange = (
            "delta"
            if ring_mode == "sharded" and n_node_shards > 1
            else "dense"
        )
    ell_args, delay_values, bucket_counts = _stage_ell_args(
        uniform, ell_idx, ell_delay, ell_mask, n_node_shards, block,
        bucket_min_rows,
    )
    delay_splits = len(delay_values) if delay_values else 1
    ring_extra = {
        "mode": ring_mode,
        "bytes_per_chip": ring_bytes,
        "slots": ring,
        "delay_splits": delay_splits,
        "degree_buckets": bucket_counts,
    }
    n_loc = n_padded // n_node_shards
    if exchange in ("delta", "hub"):
        from p2p_gossip_tpu.parallel import exchange as exch

        need, need_counts = exch.cached_flood_plan(
            ell_idx, ell_mask, n_node_shards, aux_cache=aux_cache
        )
        max_cut = int(need_counts.max()) if need_counts.size else 0
        hub_ops = None
        hub_report = None
        if exchange == "hub":
            hplan = exch.plan_hub_split(
                need, need_counts, n_node_shards, n_loc, w,
                delay_splits, hub_rows=hub_rows,
            )
            hub_report = hplan["report"]
            need = hplan["need_tail"]
            capacity = hplan["capacity"]
            if hplan["hub_count"] > 0:
                hub_ops = (
                    hplan["hub_count"], hplan["hub_local"],
                    hplan["hub_global"],
                )
        else:
            capacity = exch.delta_capacity(
                max(max_cut, 1), n_loc, w, delay_splits,
            )
        aggregate = exch.choose_aggregate(n_node_shards, capacity)
        exchange_extra = {
            "mode": exchange,
            "capacity": capacity,
            "aggregated": aggregate,
            "max_cut_rows": max_cut,
            "modeled_dense_words_per_tick": exch.modeled_exchange_words_per_tick(
                "dense" if ring_mode == "sharded" else "replicated",
                n_shards=n_node_shards, n_loc=n_loc, w=w,
                delay_splits=delay_splits,
            ),
            "modeled_delta_words_per_tick": (
                hub_report["modeled_delta_words_per_tick"]
                if hub_report is not None
                else exch.modeled_exchange_words_per_tick(
                    "delta", n_shards=n_node_shards, n_loc=n_loc, w=w,
                    capacity=capacity,
                )
            ),
        }
        if hub_report is not None:
            exchange_extra.update({
                "hub_count": hub_report["hub_count"],
                "hub_rows_forced": hub_report["hub_rows_forced"],
                "crossover_h": hub_report["crossover_h"],
                "modeled_hub_words_per_tick":
                    hub_report["modeled_hub_words_per_tick"],
            })
        exchange_plan = (
            exchange, need, capacity, exchange_extra, hub_ops, aggregate,
        )
    else:
        from p2p_gossip_tpu.parallel import exchange as exch

        mode = "dense" if ring_mode == "sharded" else "replicated"
        exchange_plan = ("dense", None, 0, {
            "mode": mode,
            "capacity": 0,
            "modeled_dense_words_per_tick": exch.modeled_exchange_words_per_tick(
                mode, n_shards=n_node_shards, n_loc=n_loc, w=w,
                delay_splits=delay_splits,
            ),
        }, None, False)
    return (
        ring_mode, ell_args, delay_values, bucket_counts, ring_extra,
        exchange_plan,
    )


def _achieved_exchange_report(
    exchange_extra: dict,
    counters,
    ticks: int,
    n_shards: int,
    n_loc: int,
    w: int,
    capacity: int,
    hub_count: int = 0,
) -> dict:
    """Fold the delta runner's achieved-traffic counters into the
    ``stats.extra['exchange']`` report: used entries / overflow writes /
    dense fallbacks summed over passes and share shards, plus the
    achieved per-chip per-tick wire words (fixed all_to_all footprint,
    plus the fixed hub all_gather block under ``exchange="hub"``, +
    amortized dense fallbacks) and the steady-state buffer occupancy —
    used entries over the wire-relevant slot count."""
    k = n_shards
    extra = dict(exchange_extra)
    extra["achieved_used_entries"] = int(counters[0])
    extra["overflow_write_ticks"] = int(counters[1])
    extra["dense_fallback_reads"] = int(counters[2])
    extra["exchange_ticks"] = int(ticks)
    if ticks:
        extra["achieved_delta_words_per_tick"] = (
            (k - 1) * (2 * capacity + hub_count * w)
            + int(counters[2]) * (k - 1) * n_loc * w / ticks
        )
        extra["delta_occupancy"] = int(counters[0]) / (
            ticks * k * max(1, k - 1) * capacity
        )
    return extra


def _stage_ell_args(
    uniform: int | None,
    ell_idx: np.ndarray,
    ell_delay: np.ndarray,
    ell_mask: np.ndarray,
    n_node_shards: int,
    block: int,
    bucket_min_rows: int,
):
    """The runner's propagation operands — layout-independent since the
    delay-split unification (the ring layout only decides WHERE each
    frontier slice is read from, in the runner's read_slice). Returns
    (ell_args flat tuple, static delay_values or None, bucket_counts).

    Operands are organized in GROUPS — one for the uniform delay, or one
    per distinct delay value (per-edge delays: `split_ell_by_delay`;
    the replicated path used to stage the full-width (idx, delay, mask)
    triple and run the dense `propagate` — at the 1M scale-free shape
    (dmax 4517) those are ~40 GB of operands plus the same again in
    in-jit blocked transposes, which OOM-killed a 125 GB host three
    times). Each group's (idx, mask) pair is then DEGREE-BUCKETED per
    node shard (`shard_bucket_ell`) so a group's gather reads ~its own
    valid entries instead of rows padded to the group's global column
    cap — on hub-skewed graphs (1M BA: dmax 4517, mean degree 6) the
    full-cap gather is ~750x masked traffic. ``ell_args`` is the flat
    tuple of per-bucket (rows, idx, mask) triples in group order;
    ``bucket_counts[g]`` says how many triples group g owns.
    """
    if uniform is not None:
        groups = [(ell_idx, ell_mask)]
        delay_values = None
    else:
        splits = split_ell_by_delay(ell_idx, ell_delay, ell_mask)
        _rss_log("delay splits built")
        delay_values = tuple(d for d, _, _ in splits)
        groups = [(i, m) for _, i, m in splits]
    ell_args: list = []
    bucket_counts: list[int] = []
    for idx_g, msk_g in groups:
        buckets = shard_bucket_ell(
            idx_g, msk_g, n_node_shards, block=block,
            min_rows=bucket_min_rows,
        )
        bucketed_entries = sum(
            idx_b.shape[0] * idx_b.shape[1] * idx_b.shape[2]
            for _, idx_b, _ in buckets
        )
        cap_full = max(
            (idx_b.shape[2] for _, idx_b, _ in buckets), default=1
        )
        direct_entries = idx_g.shape[0] * min(cap_full, idx_g.shape[1])
        if bucketed_entries > 0.75 * direct_entries:
            # Uniform-degree group: bucketing saves <25% of the gather
            # while adding per-bucket dispatch and a scatter per tick —
            # measured a 22% sharded-leg regression on the 1M ER mesh
            # (dmax 1164, mean degree ~1000). Stage the direct
            # full-width pair instead (bucket_counts 0 = the runner
            # consumes it without the bucket machinery); columns still
            # trim to the group's block-rounded max count. Hub-skewed
            # groups (1M BA: 750x full-cap waste) keep the buckets.
            cap = min(cap_full, idx_g.shape[1])
            bucket_counts.append(0)
            ell_args.extend((
                np.ascontiguousarray(idx_g[:, :cap]),
                np.ascontiguousarray(msk_g[:, :cap]),
            ))
            continue
        bucket_counts.append(len(buckets))
        for rows_b, idx_b, msk_b in buckets:
            ell_args.extend((rows_b, idx_b, msk_b))
    _rss_log("degree buckets staged")
    return tuple(ell_args), delay_values, tuple(bucket_counts)


def _stage_sharded_inputs(
    graph: Graph,
    ell_delays: np.ndarray | None,
    constant_delay: int,
    mesh: Mesh,
    block: int | None,
    churn,
):
    """The host-side staging shared by run_sharded_sim and
    run_sharded_flood_coverage: padded ELL arrays, block auto-resolution
    (the swept TPU optimum capped by the staged max degree; results are
    bitwise-identical for any block), and churn intervals padded with
    their node rows."""
    n_node_shards = mesh.shape[NODES_AXIS]
    ell_idx, ell_delay, ell_mask, degree, ring, uniform = _padded_device_graph(
        graph, ell_delays, constant_delay, n_node_shards
    )
    n_padded = ell_idx.shape[0]
    if block is None:
        block = tuned_degree_block(ell_idx.shape[1], mesh.devices.flat)
    churn_start, churn_end = _padded_churn(churn, n_padded, n_node_shards)
    return (
        ell_idx, ell_delay, ell_mask, degree, ring, uniform, n_padded,
        block, churn_start, churn_end,
    )


@functools.lru_cache(maxsize=32)
def build_sharded_runner(
    mesh: Mesh,
    n_padded: int,
    ring_size: int,
    chunk_size: int,
    horizon: int,
    block: int = DEFAULT_DEGREE_BLOCK,
    uniform_delay: int | None = None,
    num_snaps: int = 0,
    loss: tuple | None = None,
    record_coverage: bool = False,
    cov_slots: int | None = None,
    ring_mode: str = "replicated",
    delay_values: tuple | None = None,
    connect_tick: int = 0,
    bucket_counts: tuple = (1,),
    telemetry_on: bool = False,
    exchange_mode: str = "dense",
    delta_capacity: int = 0,
    hub_count: int = 0,
    delta_aggregate: bool = False,
    replica_axis: str | None = None,
    local_replicas: int = 1,
    per_replica_loss: bool = False,
    async_k: int = 0,
):
    """Compile the per-pass runner: each shares-shard processes its own
    ``chunk_size`` shares over the row-sharded graph, from the chunk's first
    generation tick to quiescence. Memoized so repeated calls with the same
    mesh/shapes reuse the jitted executable.

    ``replica_axis`` switches the runner to CAMPAIGN mode over a
    factorized ``(replica_axis, nodes)`` mesh (mesh.make_mesh(replicas=…)):
    the first mesh axis carries seed-ensemble replicas instead of share
    shards, and the SAME tick step is ``jax.vmap``ed over each replica
    shard's ``local_replicas`` batch inside ONE shared while_loop (vmap of
    the whole solo loop would trigger JAX's batched-while transform —
    per-element selects on every carried array, the ~4x cost
    batch/campaign.py measured). Per-replica operands grow a leading
    replica dim: origins/gen_ticks (R, chunk) sharded over the replica
    axis, churn intervals (R, n_padded, K), and — with
    ``per_replica_loss`` — one traced uint32 loss seed per replica
    appended after ``snap_ticks`` (the static ``loss`` pair is then
    (threshold, None); the traced seed feeds the same erasure coin, so a
    solo run with that static seed matches bitwise). Outputs stay
    per-replica — no counter psum over the first axis — giving global
    (R, n_padded) counters, (R, horizon, cov_slots) coverage, per-replica
    telemetry/digest rings, and (R, 8) delta counters. The loop runs to
    the SLOWEST replica's quiescence; a replica past its own has an
    all-zero frontier, so every extra tick is an exact identity — replica
    r is bitwise-identical to its solo sharded run. Second return value
    is the per-replica pass width (``chunk_size``).

    The first runner argument is the flat ``ell_args`` tuple staged by
    `_stage_ell_args` for (``uniform_delay``, ``delay_values``,
    ``bucket_counts``); its layout — per-group degree buckets of
    (rows, idx, mask) triples — is part of the compiled signature.

    ``num_snaps`` > 0 additionally returns (num_snaps, n_loc) received
    counts captured when the tick counter reaches each entry of the
    ``snap_ticks`` input — periodic-stats boundaries, same timing as the
    sync engine (totals over all ticks strictly before the boundary).

    ``record_coverage`` additionally returns per-tick per-slot coverage
    (horizon, cov_slots) for the first ``cov_slots`` of this shard's share
    slots (default: all chunk_size; the flood driver restricts it to the
    live slots so dead padding isn't counted every tick) — node counts
    psum'ed over the nodes axis each tick, rows past quiescence holding
    the final (constant) coverage, exactly like the sync engine's
    coverage runs.

    ``telemetry_on`` (static, part of the memoized signature) carries a
    (horizon, NUM_METRICS) metric ring through the loop — per-tick rows
    psum'ed over the nodes axis only, so each shares-shard's ring covers
    ITS share chunk (the host emits one ring event per shard, matching
    the solo engine's one-event-per-chunk convention) — returned stacked
    per share-shard as one extra trailing output.

    ``exchange_mode`` "delta" (sharded ring only) replaces the per-delay
    slice all_gathers with the sparse frontier-delta exchange
    (parallel/exchange.py): each tick ships at most ``delta_capacity``
    changed-word entries per destination over one all_to_all, readers
    reconstruct slices by scatter + own-slice overlay, and a mesh-uniform
    per-slot overflow flag routes readers to the dense all_gather when a
    shard's delta outgrew the buffer — bitwise-identical results either
    way (OR-monotone merge). Takes one extra trailing operand (the
    (n_loc, n_shards) cut membership from `plan_flood_exchange`) and
    returns one extra trailing output: a per-share-shard (8,) uint32
    counter row [used_entries_lo, used_entries_hi, overflow_write_ticks,
    dense_fallback_reads, exchange_ticks, 0, 0, 0] for achieved-traffic
    accounting (host side: `stats.extra['exchange']`).

    ``async_k`` > 0 (sharded ring only) switches the read side to the
    bounded-staleness async path (module docstring,
    parallel/async_ticks.py): a ``landed`` carry holds one prefetched
    full-canvas slice per distinct offset ``off = max(d, K)``, issued at
    the top of the PREVIOUS tick from pre-write ring state (slot
    ``t - off`` is final and is never this tick's write slot, so the
    value equals a read-time gather — the restructure only moves the
    collective a full tick ahead of its first reader, which is what
    lets XLA overlap it with the whole tick's compute). Reads overlay
    the shard's own timely ``(t - d)`` slice onto the landed canvas, so
    intra-shard edges see delay d and cross-shard edges ``max(d, K)``
    automatically. The quiescence predicate ORs the landed carry in
    (`async_ticks.in_flight`) so termination is agreed at a common fold
    epoch. Works on both transports; requires
    ``ring_size >= max(dmax, K) + 1`` (`async_ticks.effective_ring`)."""
    campaign = replica_axis is not None
    if campaign:
        if local_replicas < 1:
            raise ValueError(
                f"local_replicas must be >= 1, got {local_replicas}"
            )
        # Campaign meshes carry replicas on axis 0, not share shards: the
        # whole chunk rides one share pass per replica.
        n_share_shards = 1
    else:
        n_share_shards = mesh.shape[SHARES_AXIS]
    if per_replica_loss and (not campaign or loss is None):
        raise ValueError(
            "per_replica_loss requires replica_axis and a loss model"
        )
    axis0 = replica_axis if campaign else SHARES_AXIS
    rb = local_replicas if campaign else 1
    n_node_shards = mesh.shape[NODES_AXIS]
    n_loc = n_padded // n_node_shards
    w = bitmask.num_words(chunk_size)
    tel = tel_rings.active(telemetry_on)
    dig = tel_digest.active(telemetry_on)
    if cov_slots is None:
        cov_slots = chunk_size
    cov_w = bitmask.num_words(cov_slots)
    sharded_ring = ring_mode == "sharded"
    hist_rows = n_loc if sharded_ring else n_padded
    # "hub" is the delta transport plus a static index-free hub block;
    # hub_count == 0 (the cost search picked pure delta) compiles the
    # plain delta program — no zero-size hub collectives.
    delta = exchange_mode in ("delta", "hub")
    hub = exchange_mode == "hub" and hub_count > 0
    if delta and not sharded_ring:
        raise ValueError(
            f"exchange_mode={exchange_mode!r} requires ring_mode='sharded'"
        )
    if delta and delta_capacity < 1:
        raise ValueError(f"delta_capacity must be >= 1, got {delta_capacity}")
    if delta:
        from p2p_gossip_tpu.parallel import exchange as exch
    # Static gather-group count (one per distinct delay value): the
    # per-tick dense exchange multiplier in the telemetry traffic row.
    n_groups = (
        1 if uniform_delay is not None
        else (len(delay_values) if delay_values else 1)
    )
    group_delays_s = (
        (uniform_delay,) if uniform_delay is not None else delay_values
    )
    if async_k > 0:
        if not sharded_ring:
            raise ValueError("async exchange requires ring_mode='sharded'")
        offs, off_index, amounts = async_ticks.group_offsets(
            group_delays_s, async_k
        )
        if offs and ring_size < max(offs) + 1:
            raise ValueError(
                f"async_k={async_k} needs ring_size >= {max(offs) + 1} "
                f"(async_ticks.effective_ring), got {ring_size}"
            )
    else:
        offs, off_index, amounts = (), (), ()
    n_offs = len(offs)
    # Dense read-time gather count per tick: one per landed slice plus
    # one per direct-read group (off == 1: K=1 delay-1 edges).
    n_dense_reads = (
        n_offs + sum(1 for i in off_index if i < 0) if async_k > 0
        else n_groups
    )

    def local_coverage(seen):
        return bitmask.coverage_per_slot(seen[:, :cov_w], cov_slots)

    def pass_fn(
        ell_args, degree, churn_start, churn_end,
        origins, gen_ticks, t_start, last_gen, snap_ticks,
        *extra_args,
    ):
        # Local shapes: ell_args arrays (n_loc, cols); churn_* (n_loc, K)
        # downtime intervals ((n_loc, 1) zeros when churn is off — the
        # compare is vacuously up); origins/gen_ticks (chunk_size,);
        # t_start/last_gen scalars (min/max over ALL slices, so loop trip
        # counts agree across devices); snap_ticks (num_snaps,) replicated.
        # Campaign mode prepends a local replica dim rb to churn_*,
        # origins and gen_ticks, and appends the per-replica loss-seed
        # vector (rb,) before the delta operand when per_replica_loss.
        if campaign and per_replica_loss:
            lseeds = extra_args[0]
            delta_args = extra_args[1:]
        else:
            lseeds = None
            delta_args = extra_args
        row_offset = lax.axis_index(NODES_AXIS).astype(jnp.int32) * n_loc
        slots = jnp.arange(chunk_size, dtype=jnp.int32)
        # (Loss-coin dst ids are built per bucket inside arrivals_for:
        # row_offset + the bucket's local rows — global ids, so every
        # mesh shape agrees with the single-device engines.)

        rstate = (
            jnp.zeros((n_loc, w), dtype=jnp.uint32),              # seen (local)
            # History ring: global rows (replicated) or local rows (sharded).
            jnp.zeros((ring_size, hist_rows, w), dtype=jnp.uint32),
            jnp.zeros((n_loc,), dtype=jnp.int32),                 # received
            jnp.zeros((n_loc,), dtype=jnp.int32),                 # sent
            jnp.zeros((num_snaps, n_loc), dtype=jnp.int32),       # snapshots
            jnp.zeros(
                (cov_slots if record_coverage else 0,),
                dtype=jnp.int32,
            ),                                                    # running cov
            jnp.zeros(
                (horizon if record_coverage else 0,
                 cov_slots if record_coverage else 0),
                dtype=jnp.int32,
            ),                                                    # coverage
        )
        if tel:
            rstate = rstate + (tel_rings.init(horizon),)          # metrics
        tel_i = 7
        dig_i = 7 + (1 if tel else 0)
        if dig:
            rstate = rstate + (tel_digest.init(horizon),)         # digests
        ex_i = 7 + (1 if tel else 0) + (1 if dig else 0)
        if delta:
            need = delta_args[0]  # (n_loc, n_shards) cut membership
            if hub:
                # Static hub membership (plan_hub_split): this shard's
                # local hub row ids (leading shard axis sliced to row 0)
                # and the replicated global ids of every shard's block.
                hub_rows_l = delta_args[1][0]
                hub_global = delta_args[2]
            rstate = rstate + (
                # Received-delta rings, slot-aligned with hist: axis 1 is
                # the SOURCE shard post all_to_all. idx -1 = empty.
                jnp.full(
                    (ring_size, n_node_shards, delta_capacity),
                    -1, dtype=jnp.int32,
                ),
                jnp.zeros(
                    (ring_size, n_node_shards, delta_capacity),
                    dtype=jnp.uint32,
                ),
                # Mesh-uniform per-slot overflow flags: readers take the
                # dense all_gather branch for flagged slots.
                jnp.zeros((ring_size,), dtype=jnp.bool_),
                # [used_lo, used_hi, overflow_writes, fallback_reads,
                #  exchange_ticks, 0, 0, 0]
                jnp.zeros((8,), dtype=jnp.uint32),
            )
        if hub:
            # Hub block ring, slot-aligned with hist: every shard's h
            # hub rows at the written tick, all_gathered at write time.
            # Unwritten slots stay zero, so overlaying them is a no-op.
            rstate = rstate + (
                jnp.zeros(
                    (ring_size, n_node_shards * hub_count, w),
                    dtype=jnp.uint32,
                ),
            )
        landed_i = (
            7 + (1 if tel else 0) + (1 if dig else 0)
            + (4 if delta else 0) + (1 if hub else 0)
        )
        if n_offs:
            # Async landed double-buffer: one prefetched full-canvas
            # slice per distinct offset, holding the completed gather of
            # ring slot (t - off) at the top of tick t. Zeros are exact
            # for any t_start: every pass starts from a zeroed ring, so
            # the slots those gathers would have read are all-zero.
            rstate = rstate + (
                jnp.zeros((n_offs, n_padded, w), dtype=jnp.uint32),
            )
        if campaign:
            # One state copy per local replica: the tick step is vmapped
            # over this leading rb axis inside the shared while_loop.
            rstate = jax.tree_util.tree_map(
                lambda a: jnp.broadcast_to(a, (rb,) + a.shape), rstate
            )
        state = (t_start,) + rstate

        def cond(state):
            t, hist = state[0], state[2]
            # Local ring rows are a subset (sharded) or a replica
            # (replicated) of the global frontier state; the mesh-wide
            # OR-reduce makes the predicate uniform either way. In
            # campaign mode the loop runs until the SLOWEST replica on
            # the mesh quiesces (extra ticks are exact identities for
            # the already-quiet replicas, see build docstring). Async
            # runs OR the landed carry in: quiescence is agreed only at
            # a common fold epoch (async_ticks.in_flight).
            in_flight = async_ticks.in_flight(
                hist, state[1 + landed_i] if n_offs else None
            )
            in_flight = lax.psum(
                in_flight.astype(jnp.int32), (axis0, NODES_AXIS)
            ) > 0
            return (t < horizon) & (in_flight | (t <= last_gen))

        def read_slice(hist, dstate, t, delay):
            """The global (t - delay) frontier: a local ring read when the
            ring is replicated, an all_gather of the local slice when it
            is sharded (the read-time dense frontier exchange, riding
            ICI) — or, on the delta path, a reconstruction from the
            received frontier-delta buffers: scatter the slot's (idx,
            val) entries onto a zero canvas and overlay this shard's own
            slice. Slots whose write overflowed the delta capacity carry
            a mesh-uniform flag and fall back to the dense all_gather —
            both branches are static-shaped."""
            slot = jnp.mod(t - delay, ring_size)
            sl = hist[slot]
            if not sharded_ring:
                return sl
            if not delta:
                return lax.all_gather(sl, NODES_AXIS, axis=0, tiled=True)
            didx_ring, dval_ring, dflag_ring = dstate[:3]

            def dense_read(_):
                return lax.all_gather(sl, NODES_AXIS, axis=0, tiled=True)

            def delta_read(_):
                recon = exch.scatter_deltas(
                    didx_ring[slot], dval_ring[slot], n_loc, w, n_padded
                )
                if hub:
                    # Hub rows never ride the tail buffers (the plan
                    # clears them from the cut): overlay the slot's
                    # gathered hub block — disjoint rows, exact .set.
                    recon = exch.overlay_hub(
                        recon, hub_global, dstate[3][slot]
                    )
                # Own rows never ride the wire (plan_flood_exchange
                # excludes them): overlay the local slice directly.
                return lax.dynamic_update_slice(recon, sl, (row_offset, 0))

            return lax.cond(dflag_ring[slot], dense_read, delta_read,
                            operand=None)

        def prefetch_landed(hist, dstate, t):
            """The async gathers for tick t+1's reads, issued at the top
            of tick t from PRE-write ring state: slot (t+1-off) was
            written at tick t+1-off <= t-1 (off >= 2) and is never this
            tick's write slot (2 <= off < ring_size), so each slice
            equals the read-time gather read_slice would have done — the
            restructure only moves the collective a full tick ahead of
            its first reader. On the delta transport the slot's
            overflow flag routes to the dense gather exactly like
            read_slice; the scatter canvas leaves own rows zero (they
            never ride the wire) and the dense branch's own rows are
            stale — both fine, the reader overlays its timely local
            slice either way."""
            slices = []
            for off in offs:
                slot_u = jnp.mod(t + 1 - off, ring_size)
                sl = hist[slot_u]
                if not delta:
                    slices.append(
                        lax.all_gather(sl, NODES_AXIS, axis=0, tiled=True)
                    )
                    continue
                didx_ring, dval_ring, dflag_ring = dstate[:3]

                def dense_pre(_, sl=sl):
                    return lax.all_gather(sl, NODES_AXIS, axis=0, tiled=True)

                def delta_pre(_, slot_u=slot_u):
                    recon = exch.scatter_deltas(
                        didx_ring[slot_u], dval_ring[slot_u], n_loc, w,
                        n_padded,
                    )
                    if hub:
                        # Same overlay as delta_read; own hub rows get
                        # their written-slot values, then the reader's
                        # timely own-slice overlay wins (arrivals_for).
                        recon = exch.overlay_hub(
                            recon, hub_global, dstate[3][slot_u]
                        )
                    return recon

                slices.append(lax.cond(
                    dflag_ring[slot_u], dense_pre, delta_pre, operand=None
                ))
            return jnp.stack(slices)

        def arrivals_for(hist, dstate, t, loss_cfg=loss, lseed=None,
                         landed=None):
            # One gather group per delay value (one group total under a
            # uniform delay); read_slice resolves local vs all_gathered
            # per ring layout. Within a group, the degree buckets
            # partition this shard's rows (shard_bucket_ell), so each
            # bucket gathers at its own tight column cap and one
            # mode="drop" scatter reassembles the group's arrivals
            # (padding rows carry id n_loc and fall out). Groups OR
            # together: the delay-split ELLs partition the edge set, so
            # the OR over groups equals the full-ELL gather.
            # ``loss_cfg`` defaults to the compiled loss model; the
            # telemetry row prices loss_dropped by re-gathering with
            # loss_cfg=None (telemetry-on only). Async groups read the
            # prefetched landed canvas (slot t - max(d, K)) with the
            # shard's own timely (t - d) slice overlaid, so intra-shard
            # edges see delay d and cross-shard edges max(d, K).
            group_delays = group_delays_s
            def loss_dst_ids(local_rows):
                # THE global-id convention the loss coin hashes (shared
                # with the single-device engines): shard row offset +
                # local row id. One definition for both gather branches.
                if loss_cfg is None:
                    return None
                return row_offset + local_rows

            acc = jnp.zeros((n_loc, w), dtype=jnp.uint32)
            pos = 0
            for gi, dval in enumerate(group_delays):
                if n_offs and off_index[gi] >= 0:
                    sl = lax.dynamic_update_slice(
                        landed[off_index[gi]],
                        hist[jnp.mod(t - dval, ring_size)],
                        (row_offset, 0),
                    )
                else:
                    sl = read_slice(hist, dstate, t, dval)
                if bucket_counts[gi] == 0:
                    # Direct full-width pair (uniform-degree group —
                    # bucketing would save <25%, see _stage_ell_args):
                    # rows are 0..n_loc-1 in order, no scatter needed.
                    idx_g, msk_g = ell_args[pos: pos + 2]
                    pos += 2
                    acc = acc | gather_or_frontier(
                        sl, t, idx_g, msk_g,
                        block=max(1, min(block, idx_g.shape[1])),
                        loss=loss_cfg,
                        dst_ids=loss_dst_ids(
                            jnp.arange(n_loc, dtype=jnp.int32)
                        ),
                        loss_seed=lseed,
                    )
                    continue
                cat_rows, cat_parts = [], []
                for _ in range(bucket_counts[gi]):
                    rows_b, idx_b, msk_b = ell_args[pos: pos + 3]
                    pos += 3
                    # Leading shard axis: this device's slice is row 0.
                    rows_b, idx_b, msk_b = rows_b[0], idx_b[0], msk_b[0]
                    part = gather_or_frontier(
                        sl, t, idx_b, msk_b,
                        block=max(1, min(block, idx_b.shape[1])),
                        loss=loss_cfg,
                        dst_ids=loss_dst_ids(rows_b),
                        loss_seed=lseed,
                    )
                    cat_rows.append(rows_b)
                    cat_parts.append(part)
                grp = (
                    jnp.zeros((n_loc, w), dtype=jnp.uint32)
                    .at[jnp.concatenate(cat_rows)]
                    .set(jnp.concatenate(cat_parts), mode="drop")
                )
                acc = acc | grp
            return acc

        def tick(rstate, origins_r, gen_ticks_r, churn_start_r, churn_end_r,
                 lseed, t):
            # ONE replica's tick over its node shard — the solo body
            # verbatim, minus the tick counter (carried outside so the
            # campaign vmap shares it). All collectives inside address
            # NODES_AXIS only, so the vmap batches them per replica.
            seen, hist, received, sent, snaps, cov_run, cov_hist = rstate[:7]
            landed = rstate[landed_i] if n_offs else None
            if delta:
                didx_ring, dval_ring, dflag_ring, ectr = rstate[ex_i:ex_i + 4]
                hub_ring = rstate[ex_i + 4] if hub else None
                dstate = (didx_ring, dval_ring, dflag_ring) + (
                    (hub_ring,) if hub else ()
                )
                # Dense fallbacks this tick: one per read slot carrying
                # the (mesh-uniform) overflow flag — per landed offset
                # plus per direct-read group under async, per delay
                # group otherwise.
                read_backs = (
                    offs + tuple(
                        dv for gi, dv in enumerate(group_delays_s)
                        if off_index[gi] < 0
                    )
                    if n_offs else group_delays_s
                )
                fb_t = jnp.zeros((), dtype=jnp.uint32)
                for dv in read_backs:
                    fb_t = fb_t + dflag_ring[
                        jnp.mod(t - dv, ring_size)
                    ].astype(jnp.uint32)
            else:
                dstate = None
            if n_offs:
                # Issue tick t+1's gathers FIRST — no dependency on this
                # tick's compute or writes, so the collective can ride
                # the whole tick in the background.
                landed_next = prefetch_landed(hist, dstate, t)
            if num_snaps:
                snaps = jnp.where(
                    (snap_ticks == t)[:, None], received[None, :], snaps
                )
            arrivals = arrivals_for(hist, dstate, t, lseed=lseed,
                                    landed=landed)
            if tel:
                received_in = received
                arrivals_raw = arrivals  # post-loss, pre-churn wire view
                arrivals_nl = (
                    arrivals_for(hist, dstate, t, None, landed=landed)
                    if loss is not None else None
                )
            up = up_mask_jnp(churn_start_r, churn_end_r, t)
            arrivals = jnp.where(up[:, None], arrivals, jnp.uint32(0))
            local_rows = origins_r - row_offset
            # Negative indices wrap under .at[] before mode="drop" applies,
            # so shares owned by other row shards must be masked explicitly.
            in_shard = (local_rows >= 0) & (local_rows < n_loc)
            gen_active = (
                (gen_ticks_r == t)
                & in_shard
                & up[jnp.clip(local_rows, 0, n_loc - 1)]
            )
            gen_bits = bitmask.slot_scatter(n_loc, w, local_rows, slots, gen_active)
            gen_cnt = (
                jnp.zeros((n_loc,), dtype=jnp.int32)
                .at[local_rows]
                .add(gen_active.astype(jnp.int32), mode="drop")
            )
            if connect_tick:
                # Socket warm-up window (engine.sync._tick_body): the
                # pre-connect generation enters seen only — no frontier,
                # no sent charge.
                pre = t < connect_tick
                live_bits = jnp.where(pre, jnp.uint32(0), gen_bits)
                live_cnt = jnp.where(pre, 0, gen_cnt)
                seen, newly_out, received, sent = apply_tick_updates(
                    seen, arrivals, live_bits, live_cnt, received, sent, degree
                )
                seen = seen | jnp.where(pre, gen_bits, jnp.uint32(0))
            else:
                seen, newly_out, received, sent = apply_tick_updates(
                    seen, arrivals, gen_bits, gen_cnt, received, sent, degree
                )
            if sharded_ring:
                # Local write; the frontier exchange happens at READ time
                # (read_slice), so per-chip ring HBM is n_loc rows.
                hist = hist.at[jnp.mod(t, ring_size)].set(newly_out)
            else:
                # Write-time frontier exchange: local newly -> global rows.
                newly_full = lax.all_gather(
                    newly_out, NODES_AXIS, axis=0, tiled=True
                )
                hist = hist.at[jnp.mod(t, ring_size)].set(newly_full)
            if delta:
                # Write-time sparse exchange: pack this tick's changed
                # words per destination (cut-restricted, self-excluded)
                # and ship ONE all_to_all of fixed-capacity buffers —
                # post-exchange axis 0 is the source shard. A truncated
                # buffer anywhere on the mesh raises the slot's uniform
                # overflow flag so every reader takes the dense branch.
                cidx, cval, ccounts = exch.compress_deltas(
                    newly_out, need, delta_capacity,
                    aggregate=delta_aggregate,
                )
                idx_recv = lax.all_to_all(
                    cidx, NODES_AXIS, split_axis=0, concat_axis=0
                )
                val_recv = lax.all_to_all(
                    cval, NODES_AXIS, split_axis=0, concat_axis=0
                )
                ovf = lax.psum(
                    jnp.any(ccounts > delta_capacity).astype(jnp.int32),
                    NODES_AXIS,
                ) > 0
                slot_w = jnp.mod(t, ring_size)
                didx_ring = didx_ring.at[slot_w].set(idx_recv)
                dval_ring = dval_ring.at[slot_w].set(val_recv)
                dflag_ring = dflag_ring.at[slot_w].set(ovf)
                if hub:
                    # Index-free hub exchange: every shard's h hub rows
                    # ride one tiled all_gather per tick — w words per
                    # row per peer, no (idx, val) overhead, no overflow
                    # (the block is exactly sized).
                    hub_all = lax.all_gather(
                        newly_out[hub_rows_l], NODES_AXIS, axis=0,
                        tiled=True,
                    )
                    hub_ring = hub_ring.at[slot_w].set(hub_all)
                # Achieved-traffic counters (uniform within the share
                # shard): entries actually shipped mesh-wide this tick,
                # overflow write ticks, dense fallback reads, ticks.
                used_t = lax.psum(
                    jnp.sum(jnp.minimum(ccounts, delta_capacity)),
                    NODES_AXIS,
                ).astype(jnp.uint32)
                lo, hi = bitmask.add_u64(ectr[0], ectr[1], used_t)
                ectr = jnp.stack((
                    lo, hi,
                    ectr[2] + ovf.astype(jnp.uint32),
                    ectr[3] + fb_t,
                    ectr[4] + jnp.uint32(1),
                    ectr[5], ectr[6], ectr[7],
                ))
            if record_coverage:
                # Incremental, like engine.sync: newly_out bits are
                # disjoint across ticks, so the mesh-wide coverage is a
                # running sum of the local frontier's per-slot counts.
                cov_run = cov_run + lax.psum(
                    local_coverage(newly_out), NODES_AXIS
                )
                cov_hist = lax.dynamic_update_slice(
                    cov_hist, cov_run[None], (t, 0)
                )
            out = (seen, hist, received, sent, snaps, cov_run, cov_hist)
            if tel:
                # Per-chip state-slice exchange words received this tick
                # (ICI traffic model, see exchange.py): the NODES psum
                # below turns it into the mesh total for this share
                # chunk, like the other columns.
                if delta:
                    ex_words = (
                        jnp.uint32(
                            (n_node_shards - 1)
                            * (2 * delta_capacity + hub_count * w)
                        )
                        + fb_t * jnp.uint32((n_node_shards - 1) * n_loc * w)
                    )
                elif sharded_ring:
                    ex_words = jnp.uint32(
                        n_dense_reads * (n_node_shards - 1) * n_loc * w
                    )
                else:
                    ex_words = jnp.uint32((n_node_shards - 1) * n_loc * w)
                # Async staleness accounting: each group running
                # off = max(d, K) > d late charges its (off - d) amount
                # on ticks where its remote (cross-shard) view held any
                # pending bit. Same canvas on every shard, so the NODES
                # psum below scales both columns by n_node_shards — the
                # schema documents the columns as summed over node
                # shards, like the rest of the row.
                stale_t = jnp.uint32(0)
                folds_t = jnp.uint32(0)
                if n_offs and any(a > 0 for a in amounts):
                    remote_row = (
                        jnp.arange(n_padded, dtype=jnp.int32) // n_loc
                        != lax.axis_index(NODES_AXIS).astype(jnp.int32)
                    )
                    for gi, amt in enumerate(amounts):
                        if amt <= 0:
                            continue
                        pending = jnp.any(jnp.where(
                            remote_row[:, None],
                            landed[off_index[gi]], jnp.uint32(0),
                        ) != 0).astype(jnp.uint32)
                        stale_t = stale_t + jnp.uint32(amt) * pending
                        folds_t = folds_t + pending
                # Local row, psum'ed over node shards only: this shard's
                # ring describes its own share chunk system-wide.
                met_row = lax.psum(
                    tel_rings.flood_row(
                        arrivals_raw, newly_out, received - received_in,
                        degree, arrivals_lossless=arrivals_nl,
                        exchange_words=ex_words,
                        staleness=stale_t, stale_folds=folds_t,
                    ),
                    NODES_AXIS,
                )
                out = out + (tel_rings.write(rstate[tel_i], t, met_row),)
            if dig:
                # Global node ids make the salts mesh-shape-invariant;
                # the node-pad rows are all-zero and the sparse fold
                # skips them, so this equals the solo digest bit-for-bit.
                dval = tel_digest.tick_digest_sharded(
                    seen, received, sent,
                    node_ids=row_offset + jnp.arange(n_loc, dtype=jnp.int32),
                    axis_name=NODES_AXIS,
                )
                out = out + (tel_digest.write(rstate[dig_i], t, dval),)
            if delta:
                out = out + (didx_ring, dval_ring, dflag_ring, ectr)
            if hub:
                out = out + (hub_ring,)
            if n_offs:
                out = out + (landed_next,)
            return out

        if campaign:
            def body(state):
                t = state[0]
                if per_replica_loss:
                    new = jax.vmap(
                        lambda rs, o, g, cs, ce, ls:
                            tick(rs, o, g, cs, ce, ls, t)
                    )(state[1:], origins, gen_ticks,
                      churn_start, churn_end, lseeds)
                else:
                    new = jax.vmap(
                        lambda rs, o, g, cs, ce:
                            tick(rs, o, g, cs, ce, None, t)
                    )(state[1:], origins, gen_ticks, churn_start, churn_end)
                return (t + 1,) + new
        else:
            def body(state):
                return (state[0] + 1,) + tick(
                    state[1:], origins, gen_ticks, churn_start, churn_end,
                    None, state[0],
                )

        loop_out = lax.while_loop(cond, body, state)
        t = loop_out[0]
        received, sent, snaps = loop_out[3], loop_out[4], loop_out[5]
        cov_run, cov_hist = loop_out[6], loop_out[7]
        if record_coverage:
            # Rows past quiescence hold the (monotone, now constant) final
            # coverage — same convention as the sync engine.
            ticks = jnp.arange(horizon, dtype=jnp.int32)[:, None]
            if campaign:
                cov_hist = jnp.where(
                    ticks[None] >= t, cov_run[:, None, :], cov_hist
                )
            else:
                cov_hist = jnp.where(ticks >= t, cov_run[None, :], cov_hist)
        if num_snaps:
            # Boundaries at/after quiescence see the (unchanging) final
            # counts — same convention as the sync engine.
            if campaign:
                snaps = jnp.where(
                    (snap_ticks >= t)[None, :, None],
                    received[:, None, :], snaps,
                )
            else:
                snaps = jnp.where(
                    (snap_ticks >= t)[:, None], received[None, :], snaps
                )
        if not campaign:
            # Fold the independent share slices: counters add across
            # SHARES_AXIS. (Campaign mode skips this: each replica's
            # node-shard counters already cover its whole chunk.)
            received = lax.psum(received, SHARES_AXIS)
            sent = lax.psum(sent, SHARES_AXIS)
            snaps = lax.psum(snaps, SHARES_AXIS)
        outs = (received, sent, snaps, cov_hist)
        if tel:
            # Stack per share-shard: each shard's ring is its chunk's
            # telemetry (the host emits one event per shard). Campaign
            # rings already carry the leading replica axis.
            ring_out = loop_out[1 + tel_i]
            outs = outs + ((ring_out if campaign else ring_out[None]),)
        if dig:
            dg_out = loop_out[1 + dig_i]
            outs = outs + ((dg_out if campaign else dg_out[None]),)
        if delta:
            # Achieved-exchange counters, stacked per share-shard like
            # the telemetry ring (uniform across node shards).
            ec_out = loop_out[1 + ex_i + 3]
            outs = outs + ((ec_out if campaign else ec_out[None]),)
        return outs

    # Per bucket triple: rows (S, R) + idx/mask (S, R, C), all with the
    # shard axis leading — splitting it hands each device its own
    # (1, ...) slice. A 0 count is a direct full-width (idx, mask) pair
    # sharded by rows (see _stage_ell_args).
    ell_specs: tuple = ()
    for bc in bucket_counts:
        if bc == 0:
            ell_specs += (P(NODES_AXIS, None), P(NODES_AXIS, None))
        else:
            ell_specs += (
                P(NODES_AXIS, None), P(NODES_AXIS, None, None),
                P(NODES_AXIS, None, None),
            ) * bc
    if campaign:
        # Per-replica operands: (R, …) over the replica axis; churn also
        # sharded over nodes on axis 1. Outputs keep the replica axis —
        # no share fold, each replica's counters are already complete.
        sched_spec = P(replica_axis, None)
        in_specs = (
            ell_specs,            # ell_args (replicated over replicas)
            P(NODES_AXIS),        # degree
            P(replica_axis, NODES_AXIS, None),  # churn_start (R, n_pad, K)
            P(replica_axis, NODES_AXIS, None),  # churn_end
            sched_spec,           # origins (R, chunk)
            sched_spec,           # gen_ticks (R, chunk)
            P(),                  # t_start
            P(),                  # last_gen
            P(),                  # snap_ticks
        )
        if per_replica_loss:
            in_specs = in_specs + (P(replica_axis),)  # loss seeds (R,)
        if delta:
            in_specs = in_specs + (P(NODES_AXIS, None),)  # cut membership
        if hub:
            in_specs = in_specs + (
                P(NODES_AXIS, None),  # hub_local (k, h) row ids
                P(None, None),        # hub_global (k, h), replicated
            )
        out_specs: tuple = (
            P(replica_axis, NODES_AXIS),        # received (R, n_padded)
            P(replica_axis, NODES_AXIS),        # sent
            P(replica_axis, None, NODES_AXIS),  # snapshots
            P(replica_axis, None, None),        # coverage (R, horizon, slots)
        )
        if tel:
            out_specs = out_specs + (P(replica_axis, None, None),)
        if dig:
            out_specs = out_specs + (P(replica_axis, None),)
        if delta:
            out_specs = out_specs + (P(replica_axis, None),)
    else:
        in_specs = (
            ell_specs,            # ell_args (bucketed, see _stage_ell_args)
            P(NODES_AXIS),        # degree
            P(NODES_AXIS, None),  # churn_start
            P(NODES_AXIS, None),  # churn_end
            P(SHARES_AXIS),       # origins
            P(SHARES_AXIS),       # gen_ticks
            P(),                  # t_start
            P(),                  # last_gen
            P(),                  # snap_ticks
        ) + (
            ((P(NODES_AXIS, None),) if delta else ())  # cut membership
            # hub_local (k, h) row ids + replicated hub_global (k, h).
            + ((P(NODES_AXIS, None), P(None, None)) if hub else ())
        )
        out_specs = (
            P(NODES_AXIS), P(NODES_AXIS), P(None, NODES_AXIS),
            P(None, SHARES_AXIS),
        ) + (
            ((P(SHARES_AXIS, None, None),) if tel else ())
            + ((P(SHARES_AXIS, None),) if dig else ())
            + ((P(SHARES_AXIS, None),) if delta else ())  # exchange ctrs
        )
    mapped = shard_map(
        pass_fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(mapped), (
        chunk_size if campaign else n_share_shards * chunk_size
    )


# --- staticcheck audit spec (p2p_gossip_tpu/staticcheck/) -----------------

def _audit_mesh():
    """Smallest real mesh the audit can stage on this host: 2x2 when at
    least four devices exist (tests force 8 virtual CPU devices), else
    1x1 — a single TPU chip still traces the full shard_map program."""
    from p2p_gossip_tpu.parallel.mesh import make_mesh

    devices = jax.devices()
    shards = 2 if len(devices) >= 4 else 1
    return make_mesh(shards, shards, devices=devices[: shards * shards]), shards


def _audit_campaign_mesh():
    """Smallest factorized (replicas, nodes) mesh the audit can stage:
    (2 replicas x 2 nodes) when four devices exist, else (1 x 1)."""
    from p2p_gossip_tpu.parallel.mesh import make_mesh

    devices = jax.devices()
    if len(devices) >= 4:
        return make_mesh(2, devices=devices[:4], replicas=2)
    return make_mesh(1, devices=devices[:1], replicas=1)


def _audit_spec_flood_runner(
    telemetry_on: bool = False, exchange: str = "dense",
    campaign: bool = False, async_k: int = 0,
):
    """Stage + compile-build the sharded flood runner on tiny shapes and
    hand the auditor the exact mapped callable the production driver
    runs (shard_map + jit), uniform delay, sharded ring; ``exchange``
    "delta" audits the sparse frontier-delta path (both cond branches
    trace, so the dense fallback is covered too). ``campaign`` audits
    the replica-factorized mode (vmapped tick over the replica batch on
    a (replicas, nodes) mesh) — the jit surface
    batch/campaign_sharded.py dispatches. ``async_k`` > 0 audits the
    bounded-staleness landed-carry prefetch path (K-ahead reads on
    either transport, parallel/async_ticks.py)."""
    from p2p_gossip_tpu.models.topology import erdos_renyi
    from p2p_gossip_tpu.staticcheck.registry import AuditSpec
    from p2p_gossip_tpu.telemetry.schema import NUM_METRICS

    if campaign:
        from p2p_gossip_tpu.parallel.mesh import REPLICAS_AXIS

        mesh = _audit_campaign_mesh()
        local_replicas = 2
        r_batch = mesh.shape[REPLICAS_AXIS] * local_replicas
    else:
        mesh, _ = _audit_mesh()
    graph = erdos_renyi(16, 0.3, seed=0)
    chunk, horizon = 32, 16
    (ell_idx, ell_delay, ell_mask, degree, ring, uniform, n_padded, block,
     churn_start, churn_end) = _stage_sharded_inputs(
        graph, None, 1, mesh, None, None
    )
    ring = async_ticks.effective_ring(ring, async_k)
    (ring_mode, ell_args, delay_values, bucket_counts, _extra,
     exchange_plan) = _resolve_and_stage_ring(
        "sharded" if async_k else "auto", uniform, ring, n_padded,
        mesh.shape[NODES_AXIS],
        bitmask.num_words(chunk), ell_idx, ell_delay, ell_mask, block=block,
        exchange=exchange,
        # The tiny ER audit graph has no natural hubs — pin h so the
        # hub collectives and overlays actually trace.
        hub_rows=(8 if exchange == "hub" else None),
    )
    exchange_mode, need, capacity, _, hub_ops, aggregate = exchange_plan
    runner, pass_size = build_sharded_runner(
        mesh, n_padded, ring, chunk, horizon, block, uniform, 0, None,
        ring_mode=ring_mode, delay_values=delay_values,
        bucket_counts=bucket_counts, telemetry_on=telemetry_on,
        exchange_mode=exchange_mode, delta_capacity=capacity,
        hub_count=(hub_ops[0] if hub_ops else 0),
        delta_aggregate=aggregate,
        replica_axis=(REPLICAS_AXIS if campaign else None),
        local_replicas=(local_replicas if campaign else 1),
        async_k=async_k,
    )
    if campaign:
        origins = np.zeros((r_batch, pass_size), dtype=np.int32)
        gen_ticks = np.full((r_batch, pass_size), horizon, dtype=np.int32)
        gen_ticks[:, :2] = 0
        churn_start = np.zeros((r_batch, n_padded, 1), dtype=np.int32)
        churn_end = churn_start.copy()
    else:
        origins = np.zeros(pass_size, dtype=np.int32)
        gen_ticks = np.full(pass_size, horizon, dtype=np.int32)
        gen_ticks[:2] = 0
    words: tuple = (bitmask.num_words(chunk),)
    if telemetry_on:
        # Stacked per-shard digest rings are (1, horizon) uint32 — the
        # horizon is a declared minor width, like NUM_METRICS.
        words = words + (NUM_METRICS, horizon)
    args = (
        ell_args, degree, churn_start, churn_end, origins, gen_ticks,
        np.int32(0), np.int32(0), np.zeros((0,), dtype=np.int32),
    )
    if exchange_mode in ("delta", "hub"):
        args = args + (need,)
        # Delta buffers (capacity minor dim) and the (1, 8) counter row.
        words = words + (capacity, 8)
        if hub_ops:
            args = args + (hub_ops[1], hub_ops[2])
            words = words + (hub_ops[0],)
    return AuditSpec(
        fn=runner,
        args=args,
        integer_only=True,
        bitmask_words=words,
    )


from p2p_gossip_tpu.staticcheck.registry import register_entry  # noqa: E402

register_entry(
    "parallel.engine_sharded.flood_runner",
    spec=_audit_spec_flood_runner,
)
register_entry(
    "parallel.engine_sharded.flood_runner[telemetry]",
    spec=lambda: _audit_spec_flood_runner(telemetry_on=True),
)
register_entry(
    "parallel.engine_sharded.flood_runner[delta]",
    spec=lambda: _audit_spec_flood_runner(exchange="delta"),
)
register_entry(
    "parallel.engine_sharded.flood_runner[campaign]",
    spec=lambda: _audit_spec_flood_runner(campaign=True),
)
register_entry(
    "parallel.engine_sharded.flood_runner[campaign-delta]",
    spec=lambda: _audit_spec_flood_runner(exchange="delta", campaign=True),
)
register_entry(
    "parallel.engine_sharded.flood_runner[async]",
    spec=lambda: _audit_spec_flood_runner(async_k=2),
)
register_entry(
    "parallel.engine_sharded.flood_runner[async-delta]",
    spec=lambda: _audit_spec_flood_runner(exchange="delta", async_k=2),
)
register_entry(
    "parallel.engine_sharded.flood_runner[hub]",
    spec=lambda: _audit_spec_flood_runner(exchange="hub"),
)
register_entry(
    "parallel.engine_sharded.flood_runner[campaign-hub]",
    spec=lambda: _audit_spec_flood_runner(exchange="hub", campaign=True),
)
register_entry(
    "parallel.engine_sharded.flood_runner[async-hub]",
    spec=lambda: _audit_spec_flood_runner(exchange="hub", async_k=2),
)


def run_sharded_sim(
    graph: Graph,
    schedule: Schedule,
    horizon_ticks: int,
    mesh: Mesh,
    ell_delays: np.ndarray | None = None,
    constant_delay: int = 1,
    chunk_size: int = 4096,
    block: int | None = None,
    churn=None,
    snapshot_ticks: list[int] | None = None,
    loss=None,
    checkpoint_path: str | None = None,
    checkpoint_every: int = 1,
    stop_after_chunks: int | None = None,
    ring_mode: str = "auto",
    connect_tick: int = 0,
    bucket_min_rows: int = 2048,
    exchange: str = "dense",
    async_k: int = 2,
    hub_rows: int | None = None,
    aux_cache: tuple | None = None,
) -> NodeStats:
    """Drop-in counterpart of run_sync_sim/run_event_sim on a device mesh:
    identical per-node counters, any number of shares — including under a
    `models.churn.ChurnModel` (intervals shard with their node rows), a
    `models.linkloss.LinkLossModel` (the counter-based coin hashes global
    node ids, so shard boundaries don't change which messages drop), and
    with ``snapshot_ticks`` periodic-stats boundaries (identical snapshot
    values to the other engines; see run_sync_sim).

    ``chunk_size`` is per share-shard. The 4096 default keeps the bitmask
    minor dimension at the TPU's full 128-lane tile width — narrower chunks
    demote the hot gather to a measured ~15x slower path (see
    engine.sync.MIN_CHUNK_SHARES); tests use small chunks on CPU where only
    chunking semantics matter.

    ``checkpoint_path``/``checkpoint_every``/``stop_after_chunks`` give the
    same pass-boundary checkpoint/resume contract as run_sync_sim: counters
    accumulated so far are written atomically every ``checkpoint_every``
    passes, a restart with identical inputs resumes after the last
    completed pass, and a checkpoint from any different configuration
    (including a different mesh shape) is detected by fingerprint and
    ignored.

    ``ring_mode`` selects the history-ring layout (module docstring):
    "replicated", "sharded", or "auto" (default); counters are bitwise
    identical either way, and the resolved choice is reported in
    ``stats.extra['ring']`` with its per-chip byte cost.

    ``exchange`` selects the cross-shard frontier exchange: "dense"
    (slice all_gathers, the default), "delta" (sparse frontier-delta
    buffers over the cached cut structure — forces the sharded ring,
    bitwise-identical counters), "hub" (the delta transport with a
    static high-fan-out hub block shipped index-free every tick,
    `exchange.plan_hub_split` — also sharded, also bitwise-identical;
    ``hub_rows`` pins the split size, ``aux_cache`` persists the cut
    structure through the graph's npz aux cache), or "auto" (delta
    whenever the ring is sharded across >1 node shards). The resolved
    path, its modeled per-tick traffic, the host-chosen delta packing
    (``aggregated``), and the achieved counters land in
    ``stats.extra['exchange']``.

    ``exchange`` "async" / "async-dense" / "async-delta" switch to the
    bounded-staleness async read path with ``async_k`` = K (module and
    `parallel/async_ticks.py` docstrings): the engine runs up to K
    ticks ahead on locally-known bits over a prefetched landed
    double-buffer, bitwise-equal per tick to the synchronous run with
    cross-shard edge delays clamped to ``max(d, K)``
    (`async_ticks.clamp_flood_delays` builds that reference). "async"
    resolves the transport like "auto"; the ring is forced sharded and
    grows to ``max(dmax, K) + 1`` slots. ``async_k`` is ignored on the
    synchronous modes. Because K >= 2 changes results (by design —
    staleness trades ticks for overlap), the checkpoint fingerprint
    includes it."""
    chunk_size = bitmask.num_words(chunk_size) * bitmask.WORD_BITS
    transport, k_async = async_ticks.parse_exchange(exchange, async_k)
    exchange = transport
    if k_async:
        ring_mode = "sharded"
    (ell_idx, ell_delay, ell_mask, degree, ring, uniform, n_padded, block,
     churn_start, churn_end) = _stage_sharded_inputs(
        graph, ell_delays, constant_delay, mesh, block, churn
    )
    boundaries = filter_snapshot_boundaries(snapshot_ticks, horizon_ticks)
    snap_ticks_arr = np.asarray(boundaries, dtype=np.int32)
    ring = async_ticks.effective_ring(ring, k_async)
    (ring_mode, ell_args, delay_values, bucket_counts, ring_extra,
     exchange_plan) = _resolve_and_stage_ring(
        ring_mode, uniform, ring, n_padded, mesh.shape[NODES_AXIS],
        bitmask.num_words(chunk_size), ell_idx, ell_delay, ell_mask,
        block=block, bucket_min_rows=bucket_min_rows, exchange=exchange,
        hub_rows=hub_rows, aux_cache=aux_cache,
    )
    (exchange_mode, need, capacity, exchange_extra, hub_ops,
     aggregate) = exchange_plan
    delta_on = exchange_mode in ("delta", "hub")
    hub_n = hub_ops[0] if hub_ops else 0
    if k_async:
        exchange_extra.update(async_ticks.modeled_overlap_report(
            exchange_mode,
            (uniform,) if uniform is not None else delay_values,
            k_async, mesh.shape[NODES_AXIS],
            n_padded // mesh.shape[NODES_AXIS],
            bitmask.num_words(chunk_size), capacity, hub_count=hub_n,
        ))
    tel = telemetry.rings_enabled()
    runner, pass_size = build_sharded_runner(
        mesh, n_padded, ring, chunk_size, horizon_ticks, block, uniform,
        len(boundaries),
        loss.static_cfg if loss is not None else None,
        ring_mode=ring_mode, delay_values=delay_values,
        connect_tick=connect_tick, bucket_counts=bucket_counts,
        telemetry_on=tel, exchange_mode=exchange_mode,
        delta_capacity=capacity, hub_count=hub_n,
        delta_aggregate=aggregate, async_k=k_async,
    )
    n_share_shards = mesh.shape[SHARES_AXIS]
    exch_counters = np.zeros(3, dtype=np.int64)  # used, ovf, fallback
    exch_ticks = 0

    received = np.zeros(n_padded, dtype=np.int64)
    sent = np.zeros(n_padded, dtype=np.int64)
    snap_received = np.zeros((len(boundaries), n_padded), dtype=np.int64)

    checkpointer = None
    if checkpoint_path is not None:
        from p2p_gossip_tpu.utils.checkpoint import (
            ChunkCheckpointer,
            fingerprint,
        )

        # Fingerprint the caller's raw inputs (the staged layout is
        # derived deterministically from them); mesh shape is included so
        # a resume on a different mesh starts fresh — pass boundaries
        # differ, so partial counters would not line up.
        ckpt_fp = fingerprint(
            "sharded_sim", graph.n, graph.edges(), schedule.origins,
            schedule.gen_ticks, horizon_ticks, chunk_size,
            mesh.shape[SHARES_AXIS], mesh.shape[NODES_AXIS],
            ell_delays if ell_delays is not None else constant_delay,
            churn.down_start if churn is not None else None,
            churn.down_end if churn is not None else None,
            np.asarray(loss.static_cfg, dtype=np.int64)
            if loss is not None
            else None,
            *([np.asarray(boundaries, dtype=np.int64)] if boundaries else []),
            # Warm-up window changes the results; appended only when on.
            *(["connect", connect_tick] if connect_tick else []),
            # Async K >= 2 changes results (bounded staleness on
            # cross-shard edges); appended only when on so synchronous
            # fingerprints stay byte-stable across this change.
            *(["async", k_async] if k_async else []),
        )
        checkpointer = ChunkCheckpointer(
            checkpoint_path, ckpt_fp,
            {"received": received, "sent": sent,
             "snap_received": snap_received},
            checkpoint_every,
        )

    from p2p_gossip_tpu.utils.checkpoint import checkpointed_chunks

    chunks = schedule.chunk(pass_size)
    for ci, chunk in checkpointed_chunks(chunks, checkpointer, stop_after_chunks):
        live = chunk.gen_ticks < horizon_ticks
        if live.any():
            origins, gen_ticks = chunk.padded(pass_size, horizon_ticks)
            t_start = np.int32(chunk.gen_ticks[live].min())
            last_gen = np.int32(chunk.gen_ticks[live].max())
            with telemetry.span(
                "dispatch", kernel="parallel.engine_sharded.flood_runner",
                chunk=ci,
            ):
                out = runner(
                    ell_args, degree, churn_start, churn_end,
                    origins, gen_ticks, t_start, last_gen, snap_ticks_arr,
                    *((need,) if delta_on else ()),
                    *((hub_ops[1], hub_ops[2]) if hub_ops else ()),
                )
            r, s, sn = out[0], out[1], out[2]
            if tel:
                met, dstream = out[4], out[5]
            if delta_on:
                ec = np.asarray(out[-1], dtype=np.uint64)  # (shards, 8)
                exch_counters[0] += int(
                    bitmask.combine_u64(ec[:, 0], ec[:, 1]).sum()
                )
                exch_counters[1] += int(ec[:, 2].sum())
                exch_counters[2] += int(ec[:, 3].sum())
                exch_ticks += int(ec[:, 4].sum())
            with telemetry.span("d2h", chunk=ci):
                received += np.asarray(r, dtype=np.int64)
                sent += np.asarray(s, dtype=np.int64)
                if boundaries:
                    snap_received += np.asarray(sn, dtype=np.int64)
            digest_head = None
            if tel:
                met_np = np.asarray(met)
                dig_np = np.asarray(dstream)
                for k in range(n_share_shards):
                    tel_rings.emit_ring(
                        "parallel.engine_sharded.run_sharded_sim",
                        met_np[k], t0=int(t_start), chunk=ci, shard=k,
                    )
                    # Rows past quiescence were never written (zero);
                    # trim them like emit_ring does.
                    nz = np.flatnonzero(dig_np[k])
                    ticks_k = (
                        int(nz[-1]) + 1 - int(t_start) if nz.size else 0
                    )
                    tel_digest.emit_digest(
                        "parallel.engine_sharded.run_sharded_sim",
                        dig_np[k], t0=int(t_start), ticks=ticks_k,
                        chunk=ci, shard=k,
                    )
                    if k == 0 and nz.size:
                        digest_head = int(dig_np[0][nz[-1]])
            telemetry.emit_progress(
                "parallel.engine_sharded.run_sharded_sim",
                chunk=ci, chunks_total=len(chunks),
                digest_head=digest_head,
            )

    received = received[: graph.n]
    sent = sent[: graph.n]
    generated = effective_generated(schedule, horizon_ticks, churn)
    stats = NodeStats(
        generated=generated,
        received=received,
        forwarded=received.copy(),
        sent=sent,
        processed=generated + received,
        degree=graph.degree.astype(np.int64),
    )
    stats.extra["ring"] = ring_extra
    stats.extra["exchange"] = (
        _achieved_exchange_report(
            exchange_extra, exch_counters, exch_ticks,
            mesh.shape[NODES_AXIS], n_padded // mesh.shape[NODES_AXIS],
            bitmask.num_words(chunk_size), capacity, hub_count=hub_n,
        )
        if delta_on
        else exchange_extra
    )
    if snapshot_ticks is not None:
        stats.extra["snapshots"] = assemble_snapshots(
            schedule, churn, boundaries, snap_received[:, : graph.n],
            stats.degree.sum(),
        )
    return stats


def run_sharded_flood_coverage(
    graph: Graph,
    origins,
    horizon_ticks: int,
    mesh: Mesh,
    ell_delays: np.ndarray | None = None,
    constant_delay: int = 1,
    chunk_size: int = 4096,
    block: int | None = None,
    churn=None,
    loss=None,
    ring_mode: str = "auto",
    bucket_min_rows: int = 2048,
    exchange: str = "dense",
    async_k: int = 2,
    hub_rows: int | None = None,
    aux_cache: tuple | None = None,
):
    """Flood coverage-time experiment on the device mesh — the BASELINE
    north-star metric (time-to-99% coverage at 1M nodes on a v5e-8 mesh)
    with the same contract as `engine.sync.run_flood_coverage`: one share
    per origin at t=0, returns (stats, (horizon, num_origins) per-tick node
    counts). Coverage values are identical to the single-device run for
    every mesh shape (the per-tick count psums over node shards).
    ``exchange``/``async_k`` take the same values as `run_sharded_sim`,
    including the async spellings — the coverage matrix is what the
    `async_ticks.ttc_percentiles` staleness probe bounds."""
    origins = np.asarray(origins, dtype=np.int32).reshape(-1)
    s = origins.shape[0]
    n_share_shards = mesh.shape[SHARES_AXIS]
    # One pass: pad per-shard chunks so all origins fit in a single pass.
    per_shard = -(-s // n_share_shards)
    chunk_size = bitmask.num_words(max(per_shard, chunk_size)) * bitmask.WORD_BITS
    # Record only the live slots: at most min(s, chunk_size) per shard
    # (shard k holds global slots [k*chunk, (k+1)*chunk)); counting the
    # dead padding every tick would cost up to chunk_size/s extra work.
    cov_slots = bitmask.num_words(min(s, chunk_size)) * bitmask.WORD_BITS
    sched = Schedule(graph.n, origins, np.zeros(s, dtype=np.int32))
    transport, k_async = async_ticks.parse_exchange(exchange, async_k)
    exchange = transport
    if k_async:
        ring_mode = "sharded"

    (ell_idx, ell_delay, ell_mask, degree, ring, uniform, n_padded, block,
     churn_start, churn_end) = _stage_sharded_inputs(
        graph, ell_delays, constant_delay, mesh, block, churn
    )
    ring = async_ticks.effective_ring(ring, k_async)
    (ring_mode, ell_args, delay_values, bucket_counts, ring_extra,
     exchange_plan) = _resolve_and_stage_ring(
        ring_mode, uniform, ring, n_padded, mesh.shape[NODES_AXIS],
        bitmask.num_words(chunk_size), ell_idx, ell_delay, ell_mask,
        block=block, bucket_min_rows=bucket_min_rows, exchange=exchange,
        hub_rows=hub_rows, aux_cache=aux_cache,
    )
    (exchange_mode, need, capacity, exchange_extra, hub_ops,
     aggregate) = exchange_plan
    delta_on = exchange_mode in ("delta", "hub")
    hub_n = hub_ops[0] if hub_ops else 0
    if k_async:
        exchange_extra.update(async_ticks.modeled_overlap_report(
            exchange_mode,
            (uniform,) if uniform is not None else delay_values,
            k_async, mesh.shape[NODES_AXIS],
            n_padded // mesh.shape[NODES_AXIS],
            bitmask.num_words(chunk_size), capacity, hub_count=hub_n,
        ))
    _rss_log("ring staged")
    tel = telemetry.rings_enabled()
    runner, pass_size = build_sharded_runner(
        mesh, n_padded, ring, chunk_size, horizon_ticks, block, uniform,
        0, loss.static_cfg if loss is not None else None, True, cov_slots,
        ring_mode=ring_mode, delay_values=delay_values,
        bucket_counts=bucket_counts, telemetry_on=tel,
        exchange_mode=exchange_mode, delta_capacity=capacity,
        hub_count=hub_n, delta_aggregate=aggregate,
        async_k=k_async,
    )
    o, g_ticks = sched.padded(pass_size, horizon_ticks)
    _rss_log("runner built")
    with telemetry.span(
        "dispatch", kernel="parallel.engine_sharded.flood_runner"
    ):
        out = runner(
            ell_args, degree, churn_start, churn_end,
            o, g_ticks, np.int32(0), np.int32(0),
            np.zeros((0,), dtype=np.int32),
            *((need,) if delta_on else ()),
            *((hub_ops[1], hub_ops[2]) if hub_ops else ()),
        )
    digest_head = None
    r, snt, cov = out[0], out[1], out[3]
    if tel:
        met, dstream = out[4], out[5]
        met_np = np.asarray(met)
        dig_np = np.asarray(dstream)
        for k in range(n_share_shards):
            tel_rings.emit_ring(
                "parallel.engine_sharded.run_sharded_flood_coverage",
                met_np[k], t0=0, shard=k,
            )
            nz = np.flatnonzero(dig_np[k])
            tel_digest.emit_digest(
                "parallel.engine_sharded.run_sharded_flood_coverage",
                dig_np[k], t0=0,
                ticks=int(nz[-1]) + 1 if nz.size else 0, shard=k,
            )
            if k == 0 and nz.size:
                digest_head = int(dig_np[0][nz[-1]])
    _rss_log("runner executed")
    generated = effective_generated(sched, horizon_ticks, churn)
    received = np.asarray(r, dtype=np.int64)[: graph.n]
    stats = NodeStats(
        generated=generated,
        received=received,
        forwarded=received.copy(),
        sent=np.asarray(snt, dtype=np.int64)[: graph.n],
        processed=generated + received,
        degree=graph.degree.astype(np.int64),
    )
    # Reassemble global slot order: shard k recorded its first cov_slots
    # local slots = global slots [k*chunk, k*chunk + cov_slots).
    cov = np.asarray(cov)
    parts = []
    for k in range(n_share_shards):
        live_k = min(max(s - k * chunk_size, 0), chunk_size)
        parts.append(cov[:, k * cov_slots : k * cov_slots + live_k])
    coverage = np.concatenate(parts, axis=1)
    telemetry.emit_progress(
        "parallel.engine_sharded.run_sharded_flood_coverage",
        chunk=0, chunks_total=1, ticks_done=int(coverage.shape[0]),
        coverage_pct=(
            float(coverage[-1].mean()) / graph.n * 100.0
            if coverage.size else None
        ),
        digest_head=digest_head,
    )
    stats.extra["coverage"] = coverage
    stats.extra["ring"] = ring_extra
    if delta_on:
        ec = np.asarray(out[-1], dtype=np.uint64)  # (shards, 8)
        counters = (
            int(bitmask.combine_u64(ec[:, 0], ec[:, 1]).sum()),
            int(ec[:, 2].sum()),
            int(ec[:, 3].sum()),
        )
        exchange_extra = _achieved_exchange_report(
            exchange_extra, counters, int(ec[:, 4].sum()),
            mesh.shape[NODES_AXIS], n_padded // mesh.shape[NODES_AXIS],
            bitmask.num_words(chunk_size), capacity, hub_count=hub_n,
        )
    stats.extra["exchange"] = exchange_extra
    return stats, coverage
