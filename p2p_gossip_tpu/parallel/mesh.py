"""Device-mesh helpers.

The scale axis of the reference is one CPU core; ours is a
``jax.sharding.Mesh`` over TPU chips. Two logical axes:

- ``nodes``  — partitions graph rows (adjacency, seen-bitmask, counters);
  the per-tick frontier exchange `all_gather`s newly-frontiers along it,
  riding ICI.
- ``shares`` — partitions share chunks (independent work, embarrassingly
  parallel); counters `psum` along it at the end.
"""

from __future__ import annotations

import os

import numpy as np

import jax
from jax.sharding import Mesh

NODES_AXIS = "nodes"
SHARES_AXIS = "shares"


def make_mesh(
    n_node_shards: int | None = None,
    n_share_shards: int = 1,
    devices=None,
) -> Mesh:
    """Build a (shares, nodes) mesh. Defaults to all devices on the nodes
    axis (frontier exchange prefers the faster/denser axis)."""
    if devices is None:
        # Honor an explicitly configured default device or JAX_PLATFORMS
        # (experimental TPU plugins can register even when the user pinned
        # another platform, polluting bare jax.devices()).
        default = jax.config.jax_default_device
        platforms = os.environ.get("JAX_PLATFORMS", "")
        first = platforms.split(",")[0].strip()
        if default is not None:
            # jax_default_device may be a Device or a platform-name string.
            platform = default if isinstance(default, str) else default.platform
            devices = jax.devices(platform)
        elif first:
            try:
                devices = jax.devices(first)
            except RuntimeError:
                # Mirror JAX's own multi-entry fallback (e.g. "cuda,cpu"
                # without CUDA installed).
                devices = jax.devices()
        else:
            devices = jax.devices()
    devices = list(devices)
    if n_node_shards is None:
        n_node_shards = len(devices) // n_share_shards
    want = n_node_shards * n_share_shards
    if want > len(devices):
        raise ValueError(
            f"mesh {n_share_shards}x{n_node_shards} needs {want} devices, "
            f"have {len(devices)}"
        )
    dev_array = np.array(devices[:want]).reshape(n_share_shards, n_node_shards)
    return Mesh(dev_array, (SHARES_AXIS, NODES_AXIS))


def pad_to_multiple(x: np.ndarray, multiple: int, axis: int = 0, fill=0):
    """Pad an array so its ``axis`` length divides evenly across shards."""
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return np.pad(x, widths, constant_values=fill)
