"""Device-mesh helpers.

The scale axis of the reference is one CPU core; ours is a
``jax.sharding.Mesh`` over TPU chips. Two logical axes:

- ``nodes``  — partitions graph rows (adjacency, seen-bitmask, counters);
  the per-tick frontier exchange rides ICI along it. Two wire formats:
  the **dense** path `all_gather`s full state slices (one per delay
  group), the **delta** path ships fixed-capacity sparse
  (word-index, word-value) buffers via `all_to_all`/`all_gather` and
  falls back to a dense gather on capacity overflow
  (`parallel/exchange.py`). The shared traffic model both the cost
  observatory and `bench.py` price against is
  `exchange.modeled_exchange_words_per_tick`.
- ``shares`` — partitions share chunks (independent work, embarrassingly
  parallel); counters `psum` along it at the end.
"""

from __future__ import annotations

import os

import numpy as np

import jax
from jax.sharding import Mesh

# shard_map moved across JAX releases: new versions export it as
# `jax.shard_map` and call the replication-check kwarg `check_vma`;
# older ones (e.g. the 0.4.x installed here) only have
# `jax.experimental.shard_map.shard_map` with the kwarg named
# `check_rep`. Import it from HERE everywhere in the package —
# `from p2p_gossip_tpu.parallel.mesh import shard_map` — so the compat
# choice lives in one place.
try:
    from jax import shard_map as _shard_map_mod

    # `jax.shard_map` may be the function itself or a module exporting it.
    _shard_map = (
        _shard_map_mod
        if callable(_shard_map_mod)
        else _shard_map_mod.shard_map
    )
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

import inspect as _inspect

_SHARD_MAP_PARAMS = frozenset(_inspect.signature(_shard_map).parameters)


def shard_map(*args, **kwargs):
    """``shard_map`` with the replication-check kwarg translated to
    whatever the installed JAX spells it (check_vma <-> check_rep)."""
    if "check_vma" in kwargs and "check_vma" not in _SHARD_MAP_PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif "check_rep" in kwargs and "check_rep" not in _SHARD_MAP_PARAMS:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    return _shard_map(*args, **kwargs)

NODES_AXIS = "nodes"
SHARES_AXIS = "shares"
REPLICAS_AXIS = "replicas"

#: Default per-device HBM budget the automatic (replica, node) axis split
#: sizes the node axis against (v5e chips carry 16 GB). Overridable per
#: call (``hbm_bytes``) or process-wide via P2P_HBM_BUDGET_GB.
DEFAULT_HBM_BYTES = 16 * 10**9


def estimate_node_bytes(
    n_padded: int, max_degree: int, words: int, ring_size: int = 2
) -> int:
    """Rough whole-graph device footprint of one sharded-engine replica:
    the int32 ELL triple (idx/delay/mask at the padded column cap), the
    seen bitmask, the sharded history ring, and the three counter rows.
    Feed it to ``make_mesh(replicas="auto", node_bytes=...)`` — it only
    has to land on the right power-of-two shard count, not be exact."""
    return 4 * n_padded * (3 * max_degree + words * (1 + ring_size) + 3)


def auto_axis_split(
    n_devices: int,
    node_bytes: int | None = None,
    hbm_bytes: int | None = None,
) -> tuple[int, int]:
    """Choose the (replica_shards, node_shards) factorization of
    ``n_devices``: the SMALLEST node-shard count whose per-device slice of
    ``node_bytes`` fits the HBM budget, handing every remaining device to
    the replica axis (replica parallelism is free; node sharding buys HBM
    at the price of per-tick exchange traffic). ``node_bytes`` None means
    "fits anywhere" — all devices go to replicas. Candidate counts are the
    divisors of ``n_devices`` so the mesh always fills; if even the full
    mesh can't fit the graph, the full mesh is returned (the caller's RSS
    preflight owns that failure)."""
    if n_devices < 1:
        raise ValueError(f"need at least one device, got {n_devices}")
    if hbm_bytes is None:
        hbm_bytes = int(
            float(os.environ.get("P2P_HBM_BUDGET_GB", 0)) * 1e9
        ) or DEFAULT_HBM_BYTES
    node_shards = 1
    if node_bytes is not None:
        for d in sorted(
            d for d in range(1, n_devices + 1) if n_devices % d == 0
        ):
            node_shards = d
            if node_bytes / d <= hbm_bytes:
                break
    return n_devices // node_shards, node_shards


def make_mesh(
    n_node_shards: int | None = None,
    n_share_shards: int = 1,
    devices=None,
    replicas: int | str | None = None,
    node_bytes: int | None = None,
    hbm_bytes: int | None = None,
) -> Mesh:
    """Build a (shares, nodes) mesh. Defaults to all devices on the nodes
    axis (frontier exchange prefers the faster/denser axis).

    ``replicas`` switches to the FACTORIZED 2-D ``(replicas, nodes)``
    mesh the sharded campaign drivers (batch/campaign_sharded.py) run on:
    seed-ensemble replicas ride the first axis, graph rows the second.
    Pass an int for an explicit replica-shard count (node shards default
    to the remaining devices), or ``"auto"`` to derive the split from the
    graph footprint vs per-device HBM (``auto_axis_split``:
    ``node_bytes`` is the estimated whole-graph device footprint — see
    ``estimate_node_bytes`` — and ``hbm_bytes`` the per-device budget,
    default $P2P_HBM_BUDGET_GB or 16 GB). An explicit ``n_node_shards``
    overrides the automatic node-axis choice either way."""
    if devices is None:
        # Honor an explicitly configured default device or JAX_PLATFORMS
        # (experimental TPU plugins can register even when the user pinned
        # another platform, polluting bare jax.devices()).
        default = jax.config.jax_default_device
        platforms = os.environ.get("JAX_PLATFORMS", "")
        first = platforms.split(",")[0].strip()
        if default is not None:
            # jax_default_device may be a Device or a platform-name string.
            platform = default if isinstance(default, str) else default.platform
            devices = jax.devices(platform)
        elif first:
            try:
                devices = jax.devices(first)
            except RuntimeError:
                # Mirror JAX's own multi-entry fallback (e.g. "cuda,cpu"
                # without CUDA installed).
                devices = jax.devices()
        else:
            devices = jax.devices()
    devices = list(devices)
    if replicas is not None:
        n_dev = len(devices)
        if replicas == "auto":
            replica_shards, auto_nodes = auto_axis_split(
                n_dev, node_bytes=node_bytes, hbm_bytes=hbm_bytes
            )
            if n_node_shards is not None:  # explicit override wins
                replica_shards = n_dev // n_node_shards
            else:
                n_node_shards = auto_nodes
        else:
            replica_shards = int(replicas)
            if replica_shards < 1:
                raise ValueError(
                    f"replicas must be >= 1 or 'auto', got {replicas!r}"
                )
            if n_node_shards is None:
                n_node_shards = n_dev // replica_shards
        want = replica_shards * n_node_shards
        if want < 1 or want > n_dev:
            raise ValueError(
                f"mesh {replica_shards}x{n_node_shards} (replicas x nodes) "
                f"needs {want} devices, have {n_dev}"
            )
        dev_array = np.array(devices[:want]).reshape(
            replica_shards, n_node_shards
        )
        return Mesh(dev_array, (REPLICAS_AXIS, NODES_AXIS))
    if n_node_shards is None:
        n_node_shards = len(devices) // n_share_shards
    want = n_node_shards * n_share_shards
    if want > len(devices):
        raise ValueError(
            f"mesh {n_share_shards}x{n_node_shards} needs {want} devices, "
            f"have {len(devices)}"
        )
    dev_array = np.array(devices[:want]).reshape(n_share_shards, n_node_shards)
    return Mesh(dev_array, (SHARES_AXIS, NODES_AXIS))


def make_slot_mesh(
    slots: int,
    devices=None,
    node_bytes: int | None = None,
    hbm_bytes: int | None = None,
) -> Mesh:
    """Slot→mesh placement for the serving scheduler (serve/server.py):
    a factorized ``(replicas, nodes)`` mesh whose replica axis width
    DIVIDES the server's slot count, so every dispatch of ``slots``
    vmap rows splits evenly across replica shards (the server requires
    ``slots % replica_shards == 0``).

    Starts from ``auto_axis_split``'s HBM-driven factorization and then
    shrinks the replica axis to the largest divisor of the device count
    that also divides ``slots`` — surplus devices go to the node axis
    when that still fills the mesh, otherwise they sit out (a 6-device
    host serving slots=8 runs a 2x3 mesh, not a broken 6-wide replica
    axis)."""
    if slots < 1:
        raise ValueError(f"slots must be >= 1, got {slots}")
    probe = make_mesh(devices=devices, replicas=1)
    devices = list(probe.devices.flat)
    n_dev = len(devices)
    replica_shards, node_shards = auto_axis_split(
        n_dev, node_bytes=node_bytes, hbm_bytes=hbm_bytes
    )
    while replica_shards > 1 and slots % replica_shards != 0:
        replica_shards -= 1
        while n_dev % replica_shards != 0:
            replica_shards -= 1
        node_shards = n_dev // replica_shards
    return make_mesh(
        n_node_shards=node_shards, devices=devices, replicas=replica_shards
    )


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> tuple[int, int]:
    """Multi-host bootstrap — the role NCCL/MPI init plays in a
    GPU-cluster framework, done the JAX way: one
    ``jax.distributed.initialize`` per process, after which
    ``jax.devices()`` spans every host and the same ``shard_map`` engine
    code runs unchanged with XLA routing collectives over ICI within a
    slice and DCN across slices.

    On TPU pods (and Slurm/GKE) every argument autodetects from the
    environment — call with no arguments BEFORE anything touches the
    XLA backend. Idempotent (a second call is a no-op), and a plain
    single-process run with nothing to autodetect degrades cleanly.
    An out-of-order call (backend already initialized by earlier device
    use) raises — silently degrading a pod launch to N independent
    single-process sims would corrupt results on every host.
    Returns ``(process_index, process_count)``."""
    already = False
    try:
        from jax._src import distributed as _dist

        already = getattr(_dist.global_state, "client", None) is not None
    except ImportError:  # private-module layout changed; fall through
        pass
    if not already:
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        except RuntimeError as e:
            msg = str(e).lower()
            if "already initialized" in msg:
                pass  # raced another caller — fine
            elif "before any jax calls" in msg:
                # The ordering bug, explicit args or not: the backend
                # was touched first, so a real pod launch can no longer
                # be wired up. Never swallow.
                raise
            elif coordinator_address or num_processes:
                raise  # explicit config rejected — a real error
            # else: no-arg call with nothing to autodetect — a plain
            # single-process run; jax works fine un-distributed.
    return jax.process_index(), jax.process_count()


def make_multihost_mesh(
    n_node_shards: int | None = None,
    n_share_shards: int | None = None,
    devices=None,
) -> Mesh:
    """(shares, nodes) mesh over ALL processes' devices, axes placed for
    the interconnect hierarchy:

    - the **shares** axis spans DCN (host-to-host): share shards are
      embarrassingly parallel — zero per-tick communication, one counter
      ``psum`` at the end — so the slow network carries almost nothing;
    - the **nodes** axis stays inside each process's local devices (a
      slice's ICI): it carries the per-tick frontier exchange — the
      dense state-slice ``all_gather``s or, under ``exchange="delta"``,
      the sparse frontier-delta ``all_to_all``/``all_gather`` buffers
      (see ``exchange.modeled_exchange_words_per_tick`` for the bytes
      each path puts on the wire).

    Defaults: one share shard per process, nodes axis = one process's
    local devices (``process_is_granule`` — on a multi-host slice each
    host is its own granule, so the layout also holds when several
    processes share a slice). Falls back to the plain ``make_mesh``
    device policy when not actually distributed. ``devices`` (optional)
    pins an explicit device list — e.g. a caller that already resolved a
    host-CPU fallback set — instead of the global ``jax.devices()``."""
    nproc = jax.process_count()
    if nproc > 1:
        from jax.experimental import mesh_utils

        if devices is None:
            devices = jax.devices()
        per_process_nodes = len(jax.local_devices())
        if n_share_shards is None:
            n_share_shards = nproc
        if n_node_shards is None:
            n_node_shards = len(devices) // n_share_shards
        if (
            n_share_shards == nproc
            and n_node_shards == per_process_nodes
        ):
            # Canonical layout: granule = process, shares across
            # granules (DCN), nodes within each granule's devices.
            dev_array = mesh_utils.create_hybrid_device_mesh(
                mesh_shape=(1, n_node_shards),
                dcn_mesh_shape=(n_share_shards, 1),
                devices=devices,
                process_is_granule=True,
            )
            return Mesh(dev_array, (SHARES_AXIS, NODES_AXIS))
        return make_mesh(n_node_shards, n_share_shards, devices=devices)
    # Single process: an explicit device list passes straight through;
    # otherwise inherit make_mesh's device-selection policy
    # (JAX_PLATFORMS / default-device pollution guard) by NOT passing a
    # bare jax.devices() list down.
    return make_mesh(n_node_shards, n_share_shards or 1, devices=devices)


def pad_to_multiple(x: np.ndarray, multiple: int, axis: int = 0, fill=0):
    """Pad an array so its ``axis`` length divides evenly across shards."""
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return np.pad(x, widths, constant_values=fill)
