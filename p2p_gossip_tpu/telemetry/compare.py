"""Digest-stream alignment — the divergence bisector's comparison core.

Every engine family emits one ``digest`` event per (kernel, chunk /
replica / shard) stream: a uint32 value per executed tick of that
stream's state (telemetry/digest.py). Two engines configured
identically produce bit-identical streams, so the FIRST index where two
aligned streams differ is the first divergent tick — no re-run, no
bisection search; the recorder already holds the whole history.

This module is numpy + stdlib only (importable without jax, like the
rest of the host-side telemetry package): it reads digest events out of
a sink event list, aligns streams on their tick indices, and reports
the first divergence. `capture_event_digests` is the host twin's
capture helper — it runs the event engine with its ``on_tick`` hook and
digests each post-tick state with `digest.tick_digest_np`, which is how
the native/event engine joins a comparison against any compiled engine.

Alignment semantics: streams carry absolute tick indices (``t0`` +
offset). Only ticks PRESENT IN BOTH streams are compared — a while-exit
kernel stops writing at quiescence while a fori kernel writes identity
ticks to the horizon, and trailing identity ticks are not divergence.
The compared-tick count rides the report so "zero divergence" over an
empty overlap is visibly vacuous rather than silently green.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


def digest_streams(events, kernel: str | None = None) -> dict:
    """Collect ``digest`` events into {stream_key: {tick: value}}.

    ``stream_key`` is (kernel, chunk, replica, shard) with absent
    provenance fields as None — one entry per independent digest stream.
    ``kernel`` (substring match) restricts the sweep. Multiple events
    with the same key merge by tick index (checkpoint-resumed runs emit
    per-pass)."""
    streams: dict = {}
    for e in events:
        if e.get("type") != "digest":
            continue
        if kernel is not None and kernel not in e.get("kernel", ""):
            continue
        key = (
            e.get("kernel"), e.get("chunk"), e.get("replica"),
            e.get("shard"),
        )
        tickmap = streams.setdefault(key, {})
        t0 = int(e.get("t0", 0))
        for i, v in enumerate(e.get("values", ())):
            tickmap[t0 + i] = int(v)
    return streams


def select_stream(
    streams: dict,
    kernel: str | None = None,
    chunk=None,
    replica=None,
    shard=None,
) -> dict:
    """The one {tick: value} stream matching the given coordinates.

    A None filter accepts any value for that field. Raises KeyError when
    nothing matches and ValueError when the match is ambiguous — a
    comparison against "some stream" is not a comparison."""
    hits = []
    for (k, c, r, s), tickmap in sorted(
        streams.items(), key=lambda kv: str(kv[0])
    ):
        if kernel is not None and kernel not in (k or ""):
            continue
        if chunk is not None and c != chunk:
            continue
        if replica is not None and r != replica:
            continue
        if shard is not None and s != shard:
            continue
        hits.append(((k, c, r, s), tickmap))
    if not hits:
        raise KeyError(
            f"no digest stream matches kernel={kernel!r} chunk={chunk!r} "
            f"replica={replica!r} shard={shard!r} "
            f"(have: {sorted(streams)})"
        )
    if len(hits) > 1:
        raise ValueError(
            f"ambiguous digest stream selection: {[h[0] for h in hits]}"
        )
    return hits[0][1]


@dataclass
class Divergence:
    """One stream comparison. ``tick`` None means no divergent tick was
    found across ``compared`` common ticks."""

    tick: int | None
    compared: int
    a_value: int | None = None
    b_value: int | None = None
    only_a: int = 0           # ticks present only in stream a
    only_b: int = 0
    matched_head: int = 0     # common ticks agreeing before the divergence

    @property
    def diverged(self) -> bool:
        return self.tick is not None

    def as_dict(self) -> dict:
        return {
            "diverged": self.diverged,
            "tick": self.tick,
            "compared": self.compared,
            "a_value": self.a_value,
            "b_value": self.b_value,
            "only_a": self.only_a,
            "only_b": self.only_b,
            "matched_head": self.matched_head,
        }


def first_divergence(a: dict, b: dict) -> Divergence:
    """First common tick where two {tick: value} streams disagree."""
    common = sorted(set(a) & set(b))
    matched = 0
    for t in common:
        if int(a[t]) != int(b[t]):
            return Divergence(
                tick=int(t), compared=len(common),
                a_value=int(a[t]), b_value=int(b[t]),
                only_a=len(set(a) - set(b)), only_b=len(set(b) - set(a)),
                matched_head=matched,
            )
        matched += 1
    return Divergence(
        tick=None, compared=len(common),
        only_a=len(set(a) - set(b)), only_b=len(set(b) - set(a)),
        matched_head=matched,
    )


def inject_fault(stream: dict, tick: int, bit: int = 0) -> dict:
    """Copy of ``stream`` with one bit flipped at ``tick`` — the
    bisector's self-test: after injection, `first_divergence` against
    the original must name exactly ``tick``."""
    if tick not in stream:
        raise ValueError(
            f"fault tick {tick} not present in stream "
            f"(ticks {min(stream, default=None)}..{max(stream, default=None)})"
        )
    out = dict(stream)
    out[tick] = int(out[tick]) ^ (1 << (bit % 32))
    return out


@dataclass
class TickCapture:
    """Host-side per-tick state capture around a window — the frontier
    snapshots the bisector dumps once it has named the divergent tick."""

    digests: dict = field(default_factory=dict)    # {tick: uint32}
    received: dict = field(default_factory=dict)   # {tick: (n,) int64 copy}
    seen_counts: dict = field(default_factory=dict)  # {tick: (n,) int}


def capture_event_digests(
    graph,
    schedule,
    horizon_ticks: int,
    window: tuple[int, int] | None = None,
    **event_kwargs,
) -> TickCapture:
    """Run the event engine and digest every post-tick state with the
    numpy twin — the host side of a native/event-vs-compiled comparison.

    The digest folds the same (seen, received, sent) triple the sync
    flood kernel folds, with seen packed to the schedule's share count
    (pad-width invariance makes the word count irrelevant — see
    telemetry/digest.py). ``window=(lo, hi)`` additionally snapshots
    per-node received totals and per-node seen-set sizes for ticks in
    [lo, hi] — the frontier dump around a named divergence."""
    from p2p_gossip_tpu.engine.event import run_event_sim
    from p2p_gossip_tpu.ops import bitmask
    from p2p_gossip_tpu.telemetry import digest as tel_digest

    s = int(schedule.num_shares)
    w = bitmask.num_words(max(s, 1))
    cap = TickCapture()

    def on_tick(t, seen, received, sent):
        member = np.zeros((graph.n, max(s, 1)), dtype=bool)
        for i, shares in enumerate(seen):
            for sh in shares:
                if sh < s:
                    member[i, sh] = True
        cap.digests[t] = tel_digest.tick_digest_np(
            tel_digest.pack_seen_np(member, w), received, sent
        )
        if window is not None and window[0] <= t <= window[1]:
            cap.received[t] = np.asarray(received, dtype=np.int64).copy()
            cap.seen_counts[t] = np.asarray(
                [len(shares) for shares in seen], dtype=np.int64
            )

    run_event_sim(
        graph, schedule, horizon_ticks, on_tick=on_tick, **event_kwargs
    )
    return cap
