"""Telemetry layer: in-jit metric rings + host span tracing.

Off by default. Enable with ``P2P_TELEMETRY=<path>`` (JSONL stream) or
the CLI's ``--telemetry``; programmatic: ``telemetry.configure(path)``.
When off, the device rings compile away (same jaxpr — enforced by
`staticcheck/telemetry_off.py`) and spans are no-ops.

Layout: `schema` (event contract, jax-free), `sink` (the stream),
`spans` (host phase timers), `rings` (device per-tick aggregates),
`digest` (per-tick state digests — the flight recorder), `progress`
(per-chunk liveness beats + heartbeat file), `compare` (digest-stream
alignment for the divergence bisector), `chrometrace`
(Perfetto/chrome://tracing export). Reports: `scripts/run_report.py`,
`scripts/divergence.py`. Docs: docs/OBSERVABILITY.md.
"""

from p2p_gossip_tpu.telemetry.schema import (  # noqa: F401
    METRIC_COLUMNS,
    NUM_METRICS,
    REQUEST_EVENTS,
    SCHEMA_VERSION,
    SUPPORTED_SCHEMAS,
    validate_event,
    validate_stream,
)
from p2p_gossip_tpu.telemetry.sink import (  # noqa: F401
    configure,
    close,
    emit,
    enabled,
    event_count,
    events,
    path,
    reset,
    rings_enabled,
)
from p2p_gossip_tpu.telemetry.spans import (  # noqa: F401
    emit_counter,
    emit_jit_cache_counters,
    span,
)
from p2p_gossip_tpu.telemetry.progress import (  # noqa: F401
    configure_heartbeat,
    emit_progress,
    heartbeat_age_s,
    heartbeat_path,
    is_stale,
    read_heartbeat,
    write_heartbeat,
)
