"""Per-tick state digests — the flight recorder's black-box stream.

A digest is one uint32 per simulated tick summarizing the engine's full
state: every packed seen-bitmask word and every per-node counter is
salted (by node id, word index, and counter kind), passed through a
32-bit integer mix, and XOR-folded. XOR is associative and commutative,
so the fold order never matters — a vmapped replica lane, a shard_map
node shard (partial XOR per shard, one collective to combine), and the
solo engine all produce bit-identical digests for the same state. That
is the property the divergence bisector (`scripts/divergence.py`)
leans on: two engines that agree tick-for-tick produce identical
digest streams, and the first differing index IS the first divergent
tick.

The device digest ring rides the same STATIC ``telemetry`` flag as the
metric rings (`telemetry/rings.py`): one extra ``(capacity,)`` uint32
loop-carry plus one trailing output when the flag is up, nothing at all
when it is down. `staticcheck/telemetry_off.py` enforces the off side
by scanning the OFF trace for this module's mix constants — they appear
nowhere else in the codebase (the counter-hash coins use the murmur3
family; the digest deliberately uses lowbias32/squirrel3 constants), so
a single leaked digest op is detectable from the jaxpr alone.

A bit-exact numpy twin (`tick_digest_np`) lets host engines join the
same stream: the event engine's per-tick state (`engine/event.py`
``on_tick`` hook) digests to the same values as the sync kernel when
the two engines agree — the native-vs-sync leg of the bisector.

Digest semantics (what is folded, per tick, after the tick's updates):

- every seen word ``seen[i, k]`` salted with global node id i and
  chunk-local word index k;
- ``received[i]`` (per-node first-time receives, chunk-local);
- ``sent`` — the uint32 low word, plus the high word when the engine
  carries an emulated-u64 pair (the partnered protocols). Flood
  engines fold the low word only; their host twins must do the same.

The fold is SPARSE: an entry whose value is zero contributes nothing
(rather than mix(0 ^ salts)). That makes the digest invariant to pad
width — the campaign engine word-rounds shares to a 32-lane chunk
while the solo engine pads to its 4096-share chunk, the sharded
runners pad the node axis to the mesh — so engines with different pad
shapes produce bit-identical digests for identical live state. (It
also means sent_hi == 0 folds like an absent high word, so a
protocol engine that never overflowed its low sent word digests
compatibly with a flood-style lo-only fold of the same counters.)

Cross-engine comparisons are per share-chunk: the solo engine digests
each chunk's state separately (one stream per chunk), the sharded
runners digest each share-shard's pass (shard k == chunk k), and the
campaign engine digests each replica lane (replica r == the solo run
seeded with seed r).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from p2p_gossip_tpu.telemetry import sink

# Mix constants (lowbias32: Ellis, "Prospecting for Hash Functions") and
# lane salts (squirrel3 noise constants). These values are the digest's
# jaxpr signature — staticcheck's telemetry_off rule greps OFF traces
# for them — so they must stay unique to this module: the counter-hash
# coins (models/partnersel.py, models/linkloss.py, native/) use the
# murmur3 constant family instead.
MIX_M1 = 0x21F0AAAD
MIX_M2 = 0xD35A2D97
SALT_NODE = 0xB5297A4D
SALT_WORD = 0x68E31DA4
SALT_RECV = 0x1B56C4E9
SALT_SENT_LO = 0x7F4A7C15
SALT_SENT_HI = 0x94D049BB

#: Test fixture hook (`scripts/staticcheck.py --fixture digest`): forces
#: the digest computation on behind a down telemetry flag, which the
#: telemetry_off digest rule must flag. Never set in production.
_FIXTURE_FORCE = False


def active(telemetry) -> bool:
    """Whether kernels should carry the digest ring for this STATIC
    telemetry flag value. Mirrors `rings.active` but consults this
    module's own fixture hook, so the digest leak check can be proven
    to bite independently of the metric-ring leak check."""
    return bool(telemetry) or _FIXTURE_FORCE


def _mix_jnp(x):
    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(MIX_M1)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(MIX_M2)
    x = x ^ (x >> 15)
    return x


def _xor_all_jnp(x):
    return lax.reduce(
        x, jnp.uint32(0), lax.bitwise_xor, tuple(range(x.ndim))
    )


def _fold_sparse_jnp(values, salted):
    """XOR-fold mix(salted), skipping entries whose VALUE is zero — the
    pad-invariance rule (see module docstring)."""
    return _xor_all_jnp(
        jnp.where(
            values.astype(jnp.uint32) == 0, jnp.uint32(0), _mix_jnp(salted)
        )
    )


def tick_digest(seen, received, sent_lo, sent_hi=None, node_ids=None):
    """uint32 scalar digest of one tick's state (device side).

    ``seen`` (n, w) uint32; ``received``/``sent_lo``/``sent_hi`` (n,)
    integer arrays (any int dtype; folded as their uint32 bit pattern).
    ``node_ids`` are GLOBAL node ids (default 0..n-1) — sharded callers
    pass their row offset so every mesh shape folds identical salts.
    Omit ``sent_hi`` for engines carrying a plain int32 sent counter
    (flood); pass it for the emulated-u64 pairs (partnered protocols) —
    the sparse fold makes the two conventions agree while the high
    word is zero.
    """
    n, w = seen.shape
    if node_ids is None:
        node_ids = jnp.arange(n, dtype=jnp.uint32)
    node_salt = node_ids.astype(jnp.uint32) * jnp.uint32(SALT_NODE)
    word_salt = jnp.arange(w, dtype=jnp.uint32) * jnp.uint32(SALT_WORD)
    seen = seen.astype(jnp.uint32)
    received = received.astype(jnp.uint32)
    sent_lo = sent_lo.astype(jnp.uint32)
    h = _fold_sparse_jnp(
        seen, seen ^ word_salt[None, :] ^ node_salt[:, None]
    )
    h = h ^ _fold_sparse_jnp(
        received, received ^ node_salt ^ jnp.uint32(SALT_RECV)
    )
    h = h ^ _fold_sparse_jnp(
        sent_lo, sent_lo ^ node_salt ^ jnp.uint32(SALT_SENT_LO)
    )
    if sent_hi is not None:
        sent_hi = sent_hi.astype(jnp.uint32)
        h = h ^ _fold_sparse_jnp(
            sent_hi, sent_hi ^ node_salt ^ jnp.uint32(SALT_SENT_HI)
        )
    return h


def tick_digest_sharded(
    seen, received, sent_lo, node_ids, axis_name, sent_hi=None
):
    """Shard-local partial digest XOR-combined over the node axis.

    Inside shard_map each node shard folds its own rows (with GLOBAL
    node ids), then one ``all_gather`` of the (1,)-scalar partials plus
    a local XOR fold combines them — `lax.psum` sums, it cannot XOR.
    Bitwise equal to the single-device `tick_digest` over the full node
    set because XOR is order-independent."""
    part = tick_digest(
        seen, received, sent_lo, sent_hi=sent_hi, node_ids=node_ids
    )
    parts = lax.all_gather(part, axis_name)
    return _xor_all_jnp(parts)


# --- ring carry helpers (mirror telemetry/rings.py) -----------------------

def init(capacity: int):
    """Fresh (capacity,) uint32 digest ring for a loop carry."""
    return jnp.zeros((capacity,), dtype=jnp.uint32)


def init_batched(batch: int, capacity: int):
    """(batch, capacity) ring for vmapped campaign kernels — one digest
    lane per replica."""
    return jnp.zeros((batch, capacity), dtype=jnp.uint32)


def write(ring, t, value):
    """ring with ``value`` stored at tick ``t`` (traced index)."""
    return lax.dynamic_update_slice(
        ring, value[None].astype(jnp.uint32), (t,)
    )


def write_batched(ring, t, values):
    """(B, cap) ring with the (B,) ``values`` stored at tick ``t``."""
    return lax.dynamic_update_slice(
        ring, values[:, None].astype(jnp.uint32), (jnp.int32(0), t)
    )


# --- host (numpy) twin ----------------------------------------------------

def _mix_np(x: np.ndarray) -> np.ndarray:
    x = x.astype(np.uint32)
    with np.errstate(over="ignore"):
        x = x ^ (x >> np.uint32(16))
        x = x * np.uint32(MIX_M1)
        x = x ^ (x >> np.uint32(15))
        x = x * np.uint32(MIX_M2)
        x = x ^ (x >> np.uint32(15))
    return x


def tick_digest_np(
    seen_words: np.ndarray,
    received: np.ndarray,
    sent_lo: np.ndarray,
    sent_hi: np.ndarray | None = None,
    node_ids: np.ndarray | None = None,
) -> int:
    """Bit-exact numpy twin of `tick_digest` for host engines (the event
    engine's per-tick hook) and for unit tests pinning the device
    implementation."""
    n, _w = seen_words.shape
    if node_ids is None:
        node_ids = np.arange(n, dtype=np.uint32)

    def fold_sparse(values, salted):
        return np.bitwise_xor.reduce(
            np.where(
                values.astype(np.uint32) == 0,
                np.uint32(0),
                _mix_np(salted),
            ),
            axis=None,
        )

    with np.errstate(over="ignore"):
        node_salt = node_ids.astype(np.uint32) * np.uint32(SALT_NODE)
        word_salt = (
            np.arange(seen_words.shape[1], dtype=np.uint32)
            * np.uint32(SALT_WORD)
        )
        seen_words = seen_words.astype(np.uint32)
        received = received.astype(np.uint32)
        sent_lo = sent_lo.astype(np.uint32)
        h = fold_sparse(
            seen_words,
            seen_words ^ word_salt[None, :] ^ node_salt[:, None],
        )
        h ^= fold_sparse(
            received, received ^ node_salt ^ np.uint32(SALT_RECV)
        )
        h ^= fold_sparse(
            sent_lo, sent_lo ^ node_salt ^ np.uint32(SALT_SENT_LO)
        )
        if sent_hi is not None:
            sent_hi = sent_hi.astype(np.uint32)
            h ^= fold_sparse(
                sent_hi, sent_hi ^ node_salt ^ np.uint32(SALT_SENT_HI)
            )
    return int(h)


def pack_seen_np(member: np.ndarray, num_words: int) -> np.ndarray:
    """(n, slots) bool membership -> (n, num_words) uint32 packed words,
    matching ops/bitmask.py's contract (slot s -> word s // 32, bit
    s % 32) — how host engines rebuild the kernel's seen layout."""
    n, slots = member.shape
    out = np.zeros((n, num_words), dtype=np.uint32)
    for s in range(slots):
        if member[:, s].any():
            out[:, s // 32] |= (
                member[:, s].astype(np.uint32) << np.uint32(s % 32)
            )
    return out


# --- stream emission ------------------------------------------------------

def emit_digest(kernel: str, ring, *, t0: int, ticks: int, **provenance):
    """Emit one harvested digest ring as a ``digest`` event: rows
    [t0, t0+ticks) of the (capacity,) host copy (same slicing convention
    as `rings.emit_ring`). No-op when device rings are disabled.
    ``ticks`` is the executed-tick count (the while kernels stop at
    quiescence; rows past it were never written)."""
    if not sink.rings_enabled():
        return
    values = np.asarray(ring, dtype=np.uint32)[
        int(t0) : int(t0) + max(int(ticks), 0)
    ]
    event = {
        "type": "digest",
        "kernel": kernel,
        "t0": int(t0),
        "ticks": int(values.shape[0]),
        "values": [int(v) for v in values],
    }
    for key, val in provenance.items():
        if val is not None:
            event[key] = val
    sink.emit(event)
