"""Telemetry sink — where every event lands, and the one on/off switch.

Off by default: until ``configure()`` runs (or ``P2P_TELEMETRY=<path>``
is set in the environment), ``enabled()`` is False, spans are no-ops,
and the device metric rings compile away entirely (the engines consult
``rings_enabled()`` before threading a ring through a kernel — a static
decision, so the disabled jaxpr is byte-identical to the
pre-telemetry one; `staticcheck/telemetry_off.py` enforces that).

Two enablement axes, deliberately separate:

- ``enabled()``   — host spans + event emission. Cheap (a dict append
  or one JSONL write per event, never per tick).
- ``rings_enabled()`` — device metric rings. These change the compiled
  program (extra loop carry + per-tick integer reductions), so code
  that measures performance (bench.py) can record spans without
  perturbing the kernels it times: ``configure(path=None, rings=False)``.

Events buffer in memory when ``path`` is None and stream to a JSONL
file otherwise (line-buffered appends; one file per run). The first
event of every configured stream is the ``meta`` line.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from datetime import datetime, timezone

from p2p_gossip_tpu.telemetry.schema import SCHEMA_VERSION

ENV_VAR = "P2P_TELEMETRY"

_lock = threading.Lock()
_configured = False          # configure() ran (or env init happened)
_env_checked = False         # env auto-init attempted once
_rings = False
_path: str | None = None
_file = None
_buffer: list[dict] = []
_epoch = time.perf_counter()  # monotonic origin for span timestamps
_event_count = 0


def _meta_event(extra: dict | None = None) -> dict:
    run = {
        "argv": list(sys.argv),
        "utc": datetime.now(timezone.utc).isoformat(timespec="seconds"),
        "pid": os.getpid(),
    }
    if extra:
        run.update(extra)
    return {"type": "meta", "schema": SCHEMA_VERSION, "run": run}


def configure(
    path: str | None = None,
    *,
    rings: bool = True,
    run_info: dict | None = None,
) -> None:
    """Turn telemetry on. ``path`` streams events to that JSONL file
    (parent directory must exist); None keeps them in memory (drain with
    ``events()``). ``rings=False`` records host spans only, leaving the
    compiled kernels untouched — bench.py's mode. Reconfiguring closes
    any previous stream first."""
    global _configured, _rings, _path, _file, _epoch, _event_count
    with _lock:
        _close_locked()
        _configured = True
        _rings = bool(rings)
        _path = path
        _epoch = time.perf_counter()
        _event_count = 0
        _buffer.clear()
        if path is not None:
            _file = open(path, "a", buffering=1, encoding="utf-8")
    emit(_meta_event(run_info))


def _ensure_env_init() -> None:
    """One-shot auto-configure from P2P_TELEMETRY — the env contract the
    issue tracker/battery rely on. Explicit configure() wins."""
    global _env_checked
    if _configured or _env_checked:
        return
    with _lock:
        if _configured or _env_checked:
            return
        _env_checked = True
        path = os.environ.get(ENV_VAR, "")
    if path:
        configure(path, rings=True)


def enabled() -> bool:
    """Host-side telemetry (spans + events) on?"""
    _ensure_env_init()
    return _configured


def rings_enabled() -> bool:
    """Device-side metric rings on? Engines consult this per run and
    pass the answer as a STATIC jit argument — disabled runs trace the
    exact pre-telemetry program."""
    _ensure_env_init()
    return _configured and _rings


def epoch() -> float:
    """Monotonic origin for span timestamps (perf_counter units). Before
    any configure() the module-import instant stands in, so heartbeat-
    only runs (sink never configured) still report a sane elapsed_s."""
    return _epoch


def emit(event: dict) -> None:
    """Append one event to the active stream; silently dropped when
    telemetry is off (producers don't need to guard every call)."""
    global _event_count
    if not _configured:
        return
    with _lock:
        if not _configured:  # raced with close()
            return
        _event_count += 1
        if _file is not None:
            _file.write(json.dumps(event) + "\n")
        # Mirror into the buffer either way: in-process consumers
        # (bench.py's span summary, the tests) read events() without
        # re-parsing the file. Bounded in practice — events are per
        # chunk/span, never per tick.
        _buffer.append(event)


def events() -> list[dict]:
    """Every event emitted since configure(), in order."""
    with _lock:
        return list(_buffer)


def event_count() -> int:
    return _event_count


def path() -> str | None:
    return _path


def close() -> None:
    """Flush and disable. Idempotent."""
    with _lock:
        _close_locked()


def _close_locked() -> None:
    global _configured, _file, _rings
    if _file is not None:
        try:
            _file.flush()
            _file.close()
        except OSError:
            pass
    _file = None
    _configured = False
    _rings = False


def reset() -> None:
    """Test hook: back to the pristine off state, env re-checked on the
    next enabled() call."""
    global _env_checked, _event_count, _path
    close()
    with _lock:
        _env_checked = False
        _event_count = 0
        _path = None
        _buffer.clear()
