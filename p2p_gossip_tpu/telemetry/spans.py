"""Host span tracing — nestable monotonic-clock phase timers.

    with telemetry.span("compile", kernel="flood_runner"):
        runner = build(...)

Each closed span emits one ``span`` event: start time relative to the
sink's epoch, duration, nesting depth, and free-form attrs. The clock is
``time.perf_counter`` (monotonic — durations are immune to wall-clock
steps). Nesting is tracked per thread, so spans opened on worker threads
don't corrupt the main thread's depth.

When telemetry is off, ``span()`` yields immediately without reading the
clock — safe to leave in place on hot host paths (it still costs a
function call per use, which is why the engines only wrap per-CHUNK
work, never per-tick work; per-tick visibility is the metric rings' job).
"""

from __future__ import annotations

import contextlib
import threading
import time

from p2p_gossip_tpu.telemetry import sink

_tls = threading.local()


def _depth() -> int:
    return getattr(_tls, "depth", 0)


@contextlib.contextmanager
def span(name: str, **attrs):
    """Time a phase and emit it as a span event on exit. Nestable;
    exceptions propagate (the span still closes, attr ``error`` set)."""
    if not sink.enabled():
        yield
        return
    depth = _depth()
    _tls.depth = depth + 1
    start = time.perf_counter()
    try:
        yield
    except BaseException as e:
        attrs = {**attrs, "error": type(e).__name__}
        raise
    finally:
        dur = time.perf_counter() - start
        _tls.depth = depth
        event = {
            "type": "span",
            "name": name,
            "ts": max(start - sink.epoch(), 0.0),
            "dur": dur,
            "depth": depth,
        }
        if attrs:
            event["attrs"] = attrs
        sink.emit(event)


def emit_counter(name: str, value) -> None:
    sink.emit({"type": "counter", "name": name, "value": value})


def emit_jit_cache_counters() -> None:
    """Sample every countable registry entry's jit-cache size (the PR-3
    recompile-sentinel counters) as counter events — the run report's
    jit-cache section. No-op when telemetry is off."""
    if not sink.enabled():
        return
    from p2p_gossip_tpu.staticcheck.registry import countable_entries

    for entry in countable_entries():
        target = entry.jit_target()
        size = getattr(target, "_cache_size", None)
        if callable(size):
            try:
                emit_counter(f"jit_cache.{entry.name}", int(size()))
            except Exception:
                continue
