"""Chrome-trace (Trace Event Format) exporter — open a telemetry stream
in ``chrome://tracing`` or https://ui.perfetto.dev.

Two timelines, two pids:

- pid 1 ``host`` — every span as a complete ("ph": "X") event, ts/dur
  in microseconds on the run's monotonic clock. Nesting renders from
  the timestamps alone, exactly as the spans nested.
- pid 2 ``device ticks`` — every ring column as a counter ("ph": "C")
  series, one sample per simulated tick, with the TICK INDEX as the
  microsecond timestamp. Ticks have no wall-clock identity (they run
  inside one jit), so the device timeline is in simulation time; the
  enclosing chunk span on pid 1 says what wall interval it maps to.
  Digest streams ride pid 2 the same way; progress beats land on pid 1
  as instant events at their wall offset.

A stream need not carry every event type — a spans-only stream (bench
runs keep device rings off) exports just the host timeline, and a
rings-only stream just the device one.

Round-trip helpers (`spans_from_chrome`) exist so the export is
testable without a browser.
"""

from __future__ import annotations

import json


def to_chrome_trace(events) -> dict:
    """Telemetry events (dicts, schema.py) -> Trace Event Format dict."""
    trace: list[dict] = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "host"}},
        {"ph": "M", "pid": 2, "name": "process_name",
         "args": {"name": "device ticks"}},
    ]
    ring_seq = 0
    for event in events:
        etype = event.get("type")
        if etype == "span":
            row = {
                "ph": "X",
                "pid": 1,
                "tid": 1,
                "name": event["name"],
                "ts": round(event["ts"] * 1e6, 3),
                "dur": round(event["dur"] * 1e6, 3),
            }
            args = dict(event.get("attrs", {}))
            args["depth"] = event.get("depth", 0)
            row["args"] = args
            trace.append(row)
        elif etype == "ring":
            ring_seq += 1
            label = event["kernel"]
            for key in ("chunk", "replica", "shard"):
                if key in event:
                    label += f"[{key}={event[key]}]"
            t0 = int(event.get("t0", 0))
            for col, series in event.get("metrics", {}).items():
                for i, val in enumerate(series):
                    trace.append({
                        "ph": "C",
                        "pid": 2,
                        "name": f"{label}:{col}",
                        "ts": t0 + i,
                        "args": {col: val},
                    })
        elif etype == "digest":
            # Flight-recorder stream on the device timeline: the raw
            # uint32 per tick. The numeric value is a hash (only
            # equality means anything), but two runs' traces overlay to
            # a visual divergence point.
            label = event["kernel"]
            for key in ("chunk", "replica", "shard"):
                if key in event:
                    label += f"[{key}={event[key]}]"
            t0 = int(event.get("t0", 0))
            for i, val in enumerate(event.get("values", [])):
                trace.append({
                    "ph": "C",
                    "pid": 2,
                    "name": f"digest:{label}",
                    "ts": t0 + i,
                    "args": {"digest": val},
                })
        elif etype == "progress":
            # Liveness beats as instant events on the host timeline at
            # their wall offset — the gaps between them are the stall
            # detector's raw signal, visible at a glance.
            args = {
                k: event[k]
                for k in ("chunk", "chunks_total", "ticks_done",
                          "coverage_pct", "eta_s", "digest_head")
                if k in event
            }
            trace.append({
                "ph": "i",
                "s": "g",
                "pid": 1,
                "tid": 1,
                "name": f"progress:{event.get('kernel', '?')}",
                "ts": round(float(event.get("elapsed_s", 0.0)) * 1e6, 3),
                "args": args,
            })
        elif etype == "counter":
            trace.append({
                "ph": "C",
                "pid": 1,
                "name": event["name"],
                "ts": 0,
                "args": {"value": event["value"]},
            })
        elif etype == "meta":
            trace.append({
                "ph": "M", "pid": 1, "name": "run",
                "args": event.get("run", {}),
            })
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def spans_from_chrome(trace: dict) -> list[dict]:
    """Recover span events from an exported trace (name/ts/dur/depth in
    the original seconds units) — the round-trip the tests assert."""
    spans = []
    for row in trace.get("traceEvents", []):
        if row.get("ph") == "X" and row.get("pid") == 1:
            spans.append({
                "type": "span",
                "name": row["name"],
                "ts": row["ts"] / 1e6,
                "dur": row["dur"] / 1e6,
                "depth": row.get("args", {}).get("depth", 0),
            })
    return spans


def write_chrome_trace(events, path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(to_chrome_trace(events), f)


def load_stream(path: str) -> list[dict]:
    """Read a telemetry JSONL file into event dicts (malformed lines are
    skipped — exporting a partially-written stream should still work)."""
    events = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return events
