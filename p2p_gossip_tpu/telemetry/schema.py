"""Telemetry event schema — the one definition every producer and
consumer shares.

A telemetry stream is JSONL: one JSON object per line, each carrying a
``type`` field. Producers are the sink (`telemetry/sink.py`); consumers
are `scripts/run_report.py`, the Chrome-trace exporter
(`telemetry/chrometrace.py`), and the ci_tier1 smoke validator. This
module is deliberately jax-free so consumers can import it without
touching a backend.

Event types (SCHEMA_VERSION 2 — version 1 streams remain valid; v2 adds
the ``request``/``slot`` server events, docs/OBSERVABILITY.md):

  meta     first line of every stream: {"type": "meta", "schema": 1,
           "run": {"argv": [...], "utc": iso8601, ...}}
  span     one closed host span: {"type": "span", "name", "ts", "dur",
           "depth", "attrs"} — ts/dur in seconds on the run's monotonic
           clock (ts is the span's start relative to sink configure).
  ring     one harvested device metric ring: {"type": "ring",
           "kernel", "t0", "ticks", "columns": METRIC_COLUMNS,
           "metrics": {column: [per-tick ints]}} plus optional
           provenance ("chunk", "replica", "seed", "shard").
  counter  a scalar sample: {"type": "counter", "name", "value"} —
           used for the PR-3 recompile-sentinel jit-cache sizes and the
           compiled-cost observatory (``cost.<entry>.<field>`` names,
           scripts/cost_report.py).
  digest   one harvested per-tick state-digest ring (telemetry/digest.py):
           {"type": "digest", "kernel", "t0", "ticks",
           "values": [uint32 per executed tick]} plus the same optional
           provenance keys as ring events — the flight-recorder stream
           the divergence bisector aligns.
  progress one per-chunk liveness beat (telemetry/progress.py):
           {"type": "progress", "kernel", "elapsed_s"} plus optional
           "chunk", "chunks_total", "ticks_done", "coverage_pct",
           "eta_s", "digest_head" (8-hex-digit string), and — when the
           gossip server multiplexes runs (serve/server.py) —
           "active_requests"/"queue_depth".
  request  one request-lifecycle transition of the gossip server
           (serve/server.py): {"type": "request", "request_id",
           "event": one of REQUEST_EVENTS} plus optional "signature"
           (static-signature key), "protocol", "replicas",
           "replicas_done", "queue_depth", "turnaround_s", "reason"
           (rejections), and "cost" (the admission controller's modeled
           bytes/flops object).
  slot     one continuous-batching dispatch of the gossip server
           (serve/scheduler.py): {"type": "slot", "signature", "slots",
           "occupied", "request_ids": [...]} plus optional "batch"
           (dispatch ordinal) and "wall_s" — the slot-occupancy record
           serve_bench.py's occupancy metric reduces over.

Ring columns (uint32 on device — see docs/OBSERVABILITY.md for the
per-engine semantics and the overflow bound):

  frontier_bits   node-share bits newly entering the seen universe this
                  tick (dedup'ed; includes generations)
  frontier_nodes  nodes contributing a nonzero new frontier this tick
  newly_infected  first-time receives this tick (excludes generations —
                  sums to the run's total ``received`` counter)
  msgs_gathered   message bits arriving over links this tick, post
                  OR-reduce, post link-loss (pre node-churn drop)
  or_work         message volume the tick injects: for flood, edge
                  messages issued by the new frontier (sum of degree
                  over frontier nodes); for the partnered protocols,
                  share bits transmitted in digests/pushes this round
  loss_dropped    message bits lost in flight to the link-loss coin
                  this tick (0 when loss is off)
  exchange_words  uint32 words of frontier/state slices received over
                  the mesh interconnect this tick, totalled over node
                  shards: the dense all_gathers (x delay splits on a
                  sharded ring), the fixed delta all_to_all footprint
                  plus any dense fallbacks (exchange="delta"), or 0 on
                  a single shard. Push-direction digest traffic is NOT
                  included — this column prices the state-slice
                  exchange the dense/delta paths trade off.
  staleness       added staleness ticks consumed this tick under the
                  bounded-staleness async exchange (exchange="async",
                  parallel/async_ticks.py): the sum over async delay
                  groups x node shards of (max(d, K) - d) for each
                  group whose remote (cross-shard) frontier view held
                  any bit — i.e. how many ticks late the bits folded in
                  this tick ran, charged only when remote bits were
                  actually pending. 0 on every synchronous path and for
                  K=1 (the sync-equivalent anchor).
  stale_folds     count of stale remote-fold events this tick (async
                  delay groups with max(d, K) > d whose remote view
                  held pending bits, summed over node shards) — the
                  denominator for ``staleness``: staleness/stale_folds
                  is the mean added lateness per fold, bounded by K-1.
                  0 on every synchronous path.
"""

from __future__ import annotations

SCHEMA_VERSION = 2

#: Schema versions a consumer accepts: v1 streams (pre-server) carry no
#: request/slot events but stay valid under every v2 validator.
SUPPORTED_SCHEMAS = (1, 2)

METRIC_COLUMNS = (
    "frontier_bits",
    "frontier_nodes",
    "newly_infected",
    "msgs_gathered",
    "or_work",
    "loss_dropped",
    "exchange_words",
    "staleness",
    "stale_folds",
)
NUM_METRICS = len(METRIC_COLUMNS)

EVENT_TYPES = (
    "meta", "span", "ring", "counter", "digest", "progress", "request",
    "slot",
)

#: Request-lifecycle transitions the server emits (serve/server.py).
REQUEST_EVENTS = (
    "submitted", "admitted", "rejected", "dispatched", "preempted",
    "resumed", "done",
)


def validate_event(event) -> list[str]:
    """Schema errors for one event dict ([] = valid). Never raises on
    malformed input — every problem comes back as a message."""
    errs: list[str] = []
    if not isinstance(event, dict):
        return [f"event is {type(event).__name__}, not an object"]
    etype = event.get("type")
    if etype not in EVENT_TYPES:
        return [f"unknown event type {etype!r} (valid: {EVENT_TYPES})"]
    if etype == "meta":
        if event.get("schema") not in SUPPORTED_SCHEMAS:
            errs.append(
                f"meta.schema is {event.get('schema')!r}, expected one of "
                f"{SUPPORTED_SCHEMAS}"
            )
        if not isinstance(event.get("run"), dict):
            errs.append("meta.run must be an object")
    elif etype == "span":
        if not isinstance(event.get("name"), str) or not event.get("name"):
            errs.append("span.name must be a non-empty string")
        for key in ("ts", "dur"):
            val = event.get(key)
            if not isinstance(val, (int, float)) or val < 0:
                errs.append(f"span.{key} must be a number >= 0")
        if not isinstance(event.get("depth"), int) or event["depth"] < 0:
            errs.append("span.depth must be an int >= 0")
        if "attrs" in event and not isinstance(event["attrs"], dict):
            errs.append("span.attrs must be an object")
    elif etype == "ring":
        if not isinstance(event.get("kernel"), str) or not event.get("kernel"):
            errs.append("ring.kernel must be a non-empty string")
        if list(event.get("columns", [])) != list(METRIC_COLUMNS):
            errs.append(
                f"ring.columns must be {list(METRIC_COLUMNS)}, got "
                f"{event.get('columns')!r}"
            )
        ticks = event.get("ticks")
        if not isinstance(ticks, int) or ticks < 0:
            errs.append("ring.ticks must be an int >= 0")
        if not isinstance(event.get("t0"), int) or event.get("t0", -1) < 0:
            errs.append("ring.t0 must be an int >= 0")
        metrics = event.get("metrics")
        if not isinstance(metrics, dict):
            errs.append("ring.metrics must be an object")
        else:
            for col in METRIC_COLUMNS:
                series = metrics.get(col)
                if not isinstance(series, list):
                    errs.append(f"ring.metrics.{col} must be a list")
                elif isinstance(ticks, int) and len(series) != ticks:
                    errs.append(
                        f"ring.metrics.{col} has {len(series)} entries, "
                        f"ticks says {ticks}"
                    )
                elif not all(
                    isinstance(v, int) and v >= 0 for v in series
                ):
                    errs.append(
                        f"ring.metrics.{col} must hold non-negative ints"
                    )
    elif etype == "digest":
        if not isinstance(event.get("kernel"), str) or not event.get("kernel"):
            errs.append("digest.kernel must be a non-empty string")
        ticks = event.get("ticks")
        if not isinstance(ticks, int) or ticks < 0:
            errs.append("digest.ticks must be an int >= 0")
        if not isinstance(event.get("t0"), int) or event.get("t0", -1) < 0:
            errs.append("digest.t0 must be an int >= 0")
        values = event.get("values")
        if not isinstance(values, list):
            errs.append("digest.values must be a list")
        else:
            if isinstance(ticks, int) and len(values) != ticks:
                errs.append(
                    f"digest.values has {len(values)} entries, ticks "
                    f"says {ticks}"
                )
            if not all(
                isinstance(v, int) and 0 <= v < (1 << 32) for v in values
            ):
                errs.append("digest.values must hold uint32 ints")
    elif etype == "progress":
        if not isinstance(event.get("kernel"), str) or not event.get("kernel"):
            errs.append("progress.kernel must be a non-empty string")
        val = event.get("elapsed_s")
        if not isinstance(val, (int, float)) or val < 0:
            errs.append("progress.elapsed_s must be a number >= 0")
        for key in ("chunk", "chunks_total", "ticks_done",
                    "active_requests", "queue_depth"):
            if key in event and (
                not isinstance(event[key], int) or event[key] < 0
            ):
                errs.append(f"progress.{key} must be an int >= 0")
        for key in ("coverage_pct", "eta_s"):
            if key in event and not isinstance(event[key], (int, float)):
                errs.append(f"progress.{key} must be a number")
        if "digest_head" in event and not (
            isinstance(event["digest_head"], str)
            and len(event["digest_head"]) == 8
        ):
            errs.append("progress.digest_head must be an 8-hex-char string")
    elif etype == "counter":
        if not isinstance(event.get("name"), str) or not event.get("name"):
            errs.append("counter.name must be a non-empty string")
        if not isinstance(event.get("value"), (int, float)):
            errs.append("counter.value must be a number")
    elif etype == "request":
        rid = event.get("request_id")
        if not isinstance(rid, str) or not rid:
            errs.append("request.request_id must be a non-empty string")
        if event.get("event") not in REQUEST_EVENTS:
            errs.append(
                f"request.event is {event.get('event')!r}, expected one of "
                f"{REQUEST_EVENTS}"
            )
        for key in ("replicas", "replicas_done", "queue_depth"):
            if key in event and (
                not isinstance(event[key], int) or event[key] < 0
            ):
                errs.append(f"request.{key} must be an int >= 0")
        if "turnaround_s" in event and (
            not isinstance(event["turnaround_s"], (int, float))
            or event["turnaround_s"] < 0
        ):
            errs.append("request.turnaround_s must be a number >= 0")
        for key in ("signature", "protocol", "reason"):
            if key in event and (
                not isinstance(event[key], str) or not event[key]
            ):
                errs.append(f"request.{key} must be a non-empty string")
        if "cost" in event and not isinstance(event["cost"], dict):
            errs.append("request.cost must be an object")
    elif etype == "slot":
        sig = event.get("signature")
        if not isinstance(sig, str) or not sig:
            errs.append("slot.signature must be a non-empty string")
        slots = event.get("slots")
        if not isinstance(slots, int) or slots < 1:
            errs.append("slot.slots must be an int >= 1")
        occupied = event.get("occupied")
        if not isinstance(occupied, int) or occupied < 0:
            errs.append("slot.occupied must be an int >= 0")
        elif isinstance(slots, int) and slots >= 1 and occupied > slots:
            errs.append(
                f"slot.occupied ({occupied}) exceeds slot.slots ({slots})"
            )
        rids = event.get("request_ids")
        if not isinstance(rids, list) or not all(
            isinstance(r, str) and r for r in rids
        ):
            errs.append(
                "slot.request_ids must be a list of non-empty strings"
            )
        if "batch" in event and (
            not isinstance(event["batch"], int) or event["batch"] < 0
        ):
            errs.append("slot.batch must be an int >= 0")
        if "wall_s" in event and (
            not isinstance(event["wall_s"], (int, float))
            or event["wall_s"] < 0
        ):
            errs.append("slot.wall_s must be a number >= 0")
    return errs


def validate_stream(lines) -> list[str]:
    """Validate an iterable of JSONL lines; returns every error with its
    1-based line number prefixed. The first event must be a meta."""
    import json

    errs: list[str] = []
    first_seen = False
    for i, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            event = json.loads(line)
        except json.JSONDecodeError as e:
            errs.append(f"line {i}: not JSON ({e})")
            continue
        if not first_seen:
            first_seen = True
            if not (isinstance(event, dict) and event.get("type") == "meta"):
                errs.append("line 1: first event must be type 'meta'")
        errs.extend(f"line {i}: {msg}" for msg in validate_event(event))
    if not first_seen:
        errs.append("stream is empty (no events)")
    return errs
