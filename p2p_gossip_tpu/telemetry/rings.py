"""Device-side metric rings — fixed-shape per-tick aggregate buffers.

A metric ring is a ``(capacity, NUM_METRICS)`` uint32 array carried
through a kernel's ``lax.while_loop`` / ``lax.scan`` state; each tick
writes one row of aggregate counters (schema.METRIC_COLUMNS) at its
tick index. The ring comes back as an ordinary kernel output and is
harvested ONCE per chunk on the host (`emit_ring`) — no host callback,
no sync, nothing per-tick crosses the jit boundary.

The instrumentation is gated by a STATIC ``telemetry`` flag on every
kernel: when False (the default) no ring is created, no row is computed,
and the traced jaxpr is byte-identical to the pre-telemetry program —
`staticcheck/telemetry_off.py` asserts exactly this, and the
``telemetry`` regression fixture (`_FIXTURE_FORCE`) proves the check
still catches an always-on ring.

Overflow bound: rows are uint32, so a per-tick aggregate >= 2^32 cannot
be represented. The largest is ``or_work`` <= (frontier nodes) x dmax
and ``frontier_bits`` <= N x chunk_size; at the 1M-node ladder's
telemetry shapes (chunk 64) the bound is ~6.4e7 — 64x headroom.
Full-width 1M chunks (W=128) CAN exceed it; `u32sum` therefore
SATURATES at 2^32 - 1 instead of wrapping (exact for up to 2^24
summands — 16x the 1M node axis), so an overflowed aggregate reads as
the unmistakable sentinel 4294967295 rather than a small garbage value,
and `scripts/run_report.py` prints a wrap warning when a row saturates.
The one remaining modular edge: the sharded runners `psum` per-shard
rows, and the psum itself is plain mod-2^32 addition — a row can only
saturate per shard, so a mesh-wide aggregate between ~2^32 and
shards x 2^32 still wraps unless some shard's partial saturated first.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from p2p_gossip_tpu.ops import bitmask
from p2p_gossip_tpu.telemetry import sink
from p2p_gossip_tpu.telemetry.schema import METRIC_COLUMNS, NUM_METRICS

# Test-only: forces the rings on even when the caller passed
# telemetry=False — the seeded regression the zero-cost staticcheck
# fixture must keep flagging (scripts/staticcheck.py --fixture telemetry).
_FIXTURE_FORCE = False


def active(telemetry: bool) -> bool:
    """The one gate every instrumented kernel consults (trace-time)."""
    return bool(telemetry) or _FIXTURE_FORCE


def init(capacity: int) -> jnp.ndarray:
    """Zeroed (capacity, NUM_METRICS) ring."""
    return jnp.zeros((capacity, NUM_METRICS), dtype=jnp.uint32)


def init_batched(batch: int, capacity: int) -> jnp.ndarray:
    return jnp.zeros((batch, capacity, NUM_METRICS), dtype=jnp.uint32)


def write(ring: jnp.ndarray, t, row: jnp.ndarray) -> jnp.ndarray:
    """Write one (NUM_METRICS,) row at tick index ``t`` (traced scalar)."""
    return jax.lax.dynamic_update_slice(ring, row[None], (t, 0))


def write_batched(ring: jnp.ndarray, t, rows: jnp.ndarray) -> jnp.ndarray:
    """Write (B, NUM_METRICS) rows at tick ``t`` of a (B, cap, M) ring."""
    return jax.lax.dynamic_update_slice(ring, rows[:, None, :], (0, t, 0))


#: uint32 saturation sentinel: an aggregate that could not be
#: represented reads as exactly this value (run_report warns on it).
U32_MAX = 0xFFFFFFFF


def u32sum(x) -> jnp.ndarray:
    """Saturating-uint32 total of an integer array.

    The sum is computed exactly via four byte-limb reductions (each limb
    total stays below 2^32 for up to 2^24 summands — 16x the 1M node
    axis) and recombined with explicit carries; any carry out of the low
    word clamps the result to ``U32_MAX``. x64 stays off on device (the
    J1 staticcheck rule), so this is the widest exact sum uint32 admits.
    """
    x = x.astype(jnp.uint32).reshape(-1)
    limbs = [
        jnp.sum((x >> shift) & jnp.uint32(0xFF), dtype=jnp.uint32)
        for shift in (0, 8, 16, 24)
    ]

    def add_carry(lo, hi, add):
        new_lo = lo + add
        return new_lo, hi + (new_lo < add).astype(jnp.uint32)

    lo, hi = limbs[0], jnp.uint32(0)
    for i, limb in enumerate(limbs[1:], start=1):
        lo, hi = add_carry(lo, hi, limb << jnp.uint32(8 * i))
        hi = hi + (limb >> jnp.uint32(32 - 8 * i))
    return jnp.where(hi > 0, jnp.uint32(U32_MAX), lo)


def total_bits(words: jnp.ndarray) -> jnp.ndarray:
    """Popcount of a whole uint32 bitmask array, as a uint32 scalar."""
    return u32sum(bitmask.popcount_rows(words.reshape(-1, words.shape[-1])))


def row(
    frontier_bits,
    frontier_nodes,
    newly_infected,
    msgs_gathered,
    or_work,
    loss_dropped,
    exchange_words=0,
    staleness=0,
    stale_folds=0,
) -> jnp.ndarray:
    """Assemble one ring row in METRIC_COLUMNS order.
    ``exchange_words`` defaults to 0 — single-device kernels have no
    cross-shard state exchange to price — and ``staleness`` /
    ``stale_folds`` to 0: only the async sharded runners
    (parallel/async_ticks.py) consume late frontier views."""
    return jnp.stack(
        [
            jnp.asarray(v, dtype=jnp.uint32)
            for v in (
                frontier_bits, frontier_nodes, newly_infected,
                msgs_gathered, or_work, loss_dropped, exchange_words,
                staleness, stale_folds,
            )
        ]
    )


def flood_row(
    arrivals: jnp.ndarray,        # (N, W) post-loss gather output, pre-churn
    newly_out: jnp.ndarray,       # (N, W) the tick's new frontier (incl. gens)
    received_delta: jnp.ndarray,  # (N,) first-time receives this tick
    degree: jnp.ndarray,          # (N,) int32
    arrivals_lossless=None,       # (N, W) the same gather with loss off
    exchange_words=0,             # scalar: per-chip exchange words received
    staleness=0,                  # scalar: async added-staleness ticks
    stale_folds=0,                # scalar: async stale remote-fold events
) -> jnp.ndarray:
    """The flood engines' per-tick row (shared by the solo, campaign and
    sharded tick bodies — all three call `_tick_body`-equivalent math).
    ``loss_dropped`` is the post-OR popcount delta between the lossless
    and actual gathers, exact in message *bits* (a bit dropped on every
    one of its arriving edges counts once). ``exchange_words`` is the
    sharded runners' per-chip state-slice exchange traffic this tick
    (schema docstring); ``staleness`` the async runners' added-staleness
    ticks consumed this tick; solo engines leave both defaults 0."""
    pc_new = bitmask.popcount_rows(newly_out)
    gathered = total_bits(arrivals)
    dropped = (
        jnp.uint32(0)
        if arrivals_lossless is None
        else total_bits(arrivals_lossless) - gathered
    )
    return row(
        frontier_bits=u32sum(pc_new),
        frontier_nodes=u32sum(pc_new > 0),
        newly_infected=u32sum(received_delta),
        msgs_gathered=gathered,
        or_work=u32sum(jnp.where(pc_new > 0, degree, 0)),
        loss_dropped=dropped,
        exchange_words=exchange_words,
        staleness=staleness,
        stale_folds=stale_folds,
    )


def emit_ring(
    kernel: str,
    ring: np.ndarray,
    *,
    t0: int = 0,
    ticks: int | None = None,
    trim: bool = True,
    **provenance,
) -> None:
    """Harvest one device ring into a ``ring`` event. ``ring`` is the
    (cap, NUM_METRICS) host copy; rows [t0, t0+ticks) are emitted.
    ``ticks=None`` infers the span by trimming trailing all-zero rows
    past ``t0`` (quiescence-exited kernels leave them zero); ``trim``
    also applies when ticks is given, never trimming below 1 row.
    Extra keywords (chunk=, replica=, seed=, shard=) ride along as
    provenance fields. No-op when telemetry is off."""
    if not sink.enabled():
        return
    ring = np.asarray(ring)
    if ticks is None:
        nz = np.flatnonzero(ring[t0:].any(axis=1))
        ticks = int(nz[-1]) + 1 if nz.size else 1
    elif trim:
        window = ring[t0 : t0 + int(ticks)]
        nz = np.flatnonzero(window.any(axis=1))
        ticks = max(int(nz[-1]) + 1 if nz.size else 1, 1)
    rows = ring[t0 : t0 + int(ticks)]
    event = {
        "type": "ring",
        "kernel": kernel,
        "t0": int(t0),
        "ticks": int(rows.shape[0]),
        "columns": list(METRIC_COLUMNS),
        "metrics": {
            col: [int(v) for v in rows[:, i]]
            for i, col in enumerate(METRIC_COLUMNS)
        },
    }
    for key, val in provenance.items():
        if val is not None:
            event[key] = int(val) if isinstance(val, (np.integer,)) else val
    sink.emit(event)
