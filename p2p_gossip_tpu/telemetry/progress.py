"""Live run streaming: per-chunk progress events + a heartbeat file.

Long runs (the 1M ladder, on-chip battery stages) used to be silent
between jit dispatch and final counters. This module gives every chunk
driver two cheap liveness channels, both fed from the existing per-chunk
harvest point (no per-tick host traffic — the zero-cost contract's J3
rationale applies to liveness too):

- ``progress`` events in the telemetry JSONL stream: chunk index,
  cumulative ticks, coverage %, ETA extrapolated from elapsed wall time,
  and the head of the chunk's digest stream (when digests are on) — the
  flight recorder's cockpit view, rendered by `scripts/run_report.py`.
- a heartbeat FILE, atomically rewritten (tmp + ``os.replace``) on every
  progress emission. `scripts/tunnel_watch.py` and
  `scripts/onchip_battery.py` read its mtime age for stall detection on
  long on-chip stages: a live stage keeps the mtime fresh; a wedged
  device hang does not. The heartbeat is independent of the JSONL sink —
  set ``P2P_HEARTBEAT=<path>`` (or `configure_heartbeat`) and it works
  even with telemetry off, because liveness must not require paying for
  instrumented kernels.
"""

from __future__ import annotations

import json
import os
import threading
import time

from p2p_gossip_tpu.telemetry import sink

ENV_HEARTBEAT = "P2P_HEARTBEAT"

_lock = threading.Lock()
_heartbeat_path: str | None = None
_heartbeat_configured = False


def configure_heartbeat(path: str | None) -> None:
    """Set (or clear, with None) the heartbeat file path, overriding the
    ``P2P_HEARTBEAT`` environment variable."""
    global _heartbeat_path, _heartbeat_configured
    with _lock:
        _heartbeat_path = path
        _heartbeat_configured = True


def heartbeat_path() -> str | None:
    """The active heartbeat path: `configure_heartbeat`'s value if it was
    ever called, else ``P2P_HEARTBEAT`` (re-read per call so battery
    subprocesses inherit it without any import-order dance)."""
    with _lock:
        if _heartbeat_configured:
            return _heartbeat_path
    return os.environ.get(ENV_HEARTBEAT) or None


def write_heartbeat(payload: dict, path: str | None = None) -> None:
    """Atomically rewrite the heartbeat file: write a sibling tmp file,
    fsync, ``os.replace``. A reader never sees a torn write, and the
    file's mtime is the liveness signal (`heartbeat_age_s`)."""
    path = path if path is not None else heartbeat_path()
    if not path:
        return
    record = {
        "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "pid": os.getpid(),
        **payload,
    }
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(json.dumps(record))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError:
        # Liveness reporting must never take a run down.
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass


def read_heartbeat(path: str) -> dict | None:
    """The heartbeat payload, or None when missing/unreadable/torn."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None


def heartbeat_age_s(path: str) -> float | None:
    """Seconds since the heartbeat file was last rewritten (mtime-based,
    immune to clock text in the payload), or None when it is missing."""
    try:
        return max(0.0, time.time() - os.stat(path).st_mtime)
    except OSError:
        return None


def is_stale(path: str, max_age_s: float) -> bool:
    """True when the heartbeat is missing or older than ``max_age_s`` —
    the stall predicate the watchers act on."""
    age = heartbeat_age_s(path)
    return age is None or age > max_age_s


def emit_progress(
    kernel: str,
    *,
    chunk: int | None = None,
    chunks_total: int | None = None,
    ticks_done: int | None = None,
    coverage_pct: float | None = None,
    digest_head: int | None = None,
    active_requests: int | None = None,
    queue_depth: int | None = None,
    **provenance,
):
    """One per-chunk progress beat: a ``progress`` event into the JSONL
    sink (when enabled) and a heartbeat-file rewrite (when configured).
    ETA extrapolates elapsed wall time over completed chunks — coarse by
    design; it exists so a 6-hour battery stage is distinguishable from
    a wedge, not to forecast.

    ``active_requests``/``queue_depth`` are the gossip server's
    multiplexing counters (serve/server.py): when one process drains
    many requests, the per-chunk cadence alone can't tell "slow batch"
    from "deep queue" — the watchers' stall heuristics read these from
    the heartbeat payload to keep their thresholds meaningful."""
    hb_path = heartbeat_path()
    if not sink.enabled() and not hb_path:
        return
    elapsed = round(time.perf_counter() - sink.epoch(), 4)
    event: dict = {
        "type": "progress",
        "kernel": kernel,
        "elapsed_s": elapsed,
    }
    if chunk is not None:
        event["chunk"] = int(chunk)
    if chunks_total is not None:
        event["chunks_total"] = int(chunks_total)
        done = (int(chunk) + 1) if chunk is not None else None
        if done and chunks_total and elapsed > 0:
            frac = min(1.0, done / int(chunks_total))
            if frac > 0:
                event["eta_s"] = round(elapsed * (1.0 - frac) / frac, 2)
    if ticks_done is not None:
        event["ticks_done"] = int(ticks_done)
    if coverage_pct is not None:
        event["coverage_pct"] = round(float(coverage_pct), 4)
    if digest_head is not None:
        event["digest_head"] = f"{int(digest_head) & 0xFFFFFFFF:08x}"
    if active_requests is not None:
        event["active_requests"] = int(active_requests)
    if queue_depth is not None:
        event["queue_depth"] = int(queue_depth)
    for key, val in provenance.items():
        if val is not None:
            event[key] = val
    if sink.enabled():
        sink.emit(event)
    if hb_path:
        write_heartbeat({k: v for k, v in event.items() if k != "type"},
                        hb_path)
