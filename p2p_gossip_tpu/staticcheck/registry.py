"""Audit-entry registry — the one list of compiled surfaces to check.

Every public jit entry point (engine kernels, campaign runners, sharded
runners, ops primitives) registers here, either with the ``audited``
decorator on the function itself or an explicit ``register_entry`` call
for factory-built runners. The jaxpr auditor iterates the registry, so a
new engine that registers is audited by default — and one that doesn't
shows up as a coverage gap in the CLI's entry list rather than silently
skipping the gate.

Import-light on purpose: no jax at module scope, specs are built lazily
(the ``spec`` argument is a zero-arg callable evaluated only when the
auditor runs), so decorating a kernel costs one dict insert at import.
"""

from __future__ import annotations

import dataclasses
import typing

if typing.TYPE_CHECKING:  # pragma: no cover - typing only
    from collections.abc import Callable


@dataclasses.dataclass
class AuditSpec:
    """How to abstract-trace one entry point.

    ``args``/``kwargs`` are the concrete example operands (tiny shapes —
    the audit is abstract, values never run). ``fn`` overrides the
    registered callable for factory-built runners whose compiled object
    only exists once the spec builder has staged a mesh.

    ``integer_only`` asserts the traced computation carries no inexact
    dtype anywhere — the weak-type-promotion guard for the bitwise tick
    kernels, where a stray Python float silently upcasts whole counter
    chains to f32. ``bitmask_words`` asserts every uint32 operand/result
    of rank >= 2 in the entry's signature packs its minor axis to exactly
    that word count (ops/bitmask.py's ``num_words`` contract — slot s
    lives at word s // 32, so a mismatched minor axis means slots are
    silently truncated or padded into a different share universe).
    """

    args: tuple
    kwargs: dict = dataclasses.field(default_factory=dict)
    fn: "Callable | None" = None
    integer_only: bool = False
    bitmask_words: int | None = None


@dataclasses.dataclass
class AuditEntry:
    name: str
    fn: "Callable | None"
    spec: "Callable[[], AuditSpec]"
    count_compiles: bool = False

    def jit_target(self):
        """The object whose executable cache the recompile sentinel
        counts (jit-wrapped callables expose ``_cache_size``)."""
        return self.fn


_REGISTRY: dict[str, AuditEntry] = {}


def register_entry(
    name: str,
    fn=None,
    *,
    spec,
    count_compiles: bool = False,
) -> None:
    """Register ``fn`` (or a spec-built runner when ``fn`` is None) under
    ``name``. ``spec`` is a zero-arg callable returning an AuditSpec —
    evaluated lazily at audit time, so it may reference module globals
    defined after the registration site. Re-registration under the same
    name replaces (module reloads in tests)."""
    _REGISTRY[name] = AuditEntry(
        name=name, fn=fn, spec=spec, count_compiles=count_compiles
    )


def audited(name: str, *, spec, count_compiles: bool = False):
    """Decorator form of ``register_entry`` for directly-defined kernels:

        @audited("engine.sync._run_chunk_while", spec=lambda: _spec())
        @functools.partial(jax.jit, static_argnames=(...))
        def _run_chunk_while(...): ...

    Returns the function unchanged (stacks above ``jax.jit`` so the
    registered object is the jit wrapper the sentinel can count).
    """

    def deco(fn):
        register_entry(name, fn, spec=spec, count_compiles=count_compiles)
        return fn

    return deco


def all_entries() -> tuple[AuditEntry, ...]:
    """Registered entries in name order (deterministic reports)."""
    return tuple(_REGISTRY[k] for k in sorted(_REGISTRY))


def get_entry(name: str) -> AuditEntry:
    return _REGISTRY[name]


def countable_entries() -> tuple[AuditEntry, ...]:
    """Entries whose jit cache the recompile sentinel tracks."""
    return tuple(e for e in all_entries() if e.count_compiles)
