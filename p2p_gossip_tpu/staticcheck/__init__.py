"""Compile-time invariant auditing for the TPU engines.

The NS-3 reference gets correctness "for free" from a sequential event
loop; this rebuild instead leans on fragile compile-time invariants —
static shapes, int32/uint32 word-width discipline, traced loss seeds,
one compilation per sweep grid — that nothing used to check until a
kernel silently recompiled or a replica stream collided. This package is
the sanitizer pass for the compiled stack:

- ``registry``     — the lightweight decorator/registry every public
                     entry point self-registers with, so new engines are
                     audited by default;
- ``jaxpr_audit``  — abstract-traces each registered entry and rejects
                     64-bit dtype promotion, float leakage into the
                     integer kernels, host callbacks, device transfers,
                     dynamic shapes, and bitmask word-count mismatches
                     vs ops/bitmask.py's packing contract;
- ``recompile``    — the recompile sentinel: replays a small sweep grid
                     under a jit-cache-miss counter and fails when the
                     measured compile count drifts from the grid's
                     expected count;
- ``astlint``      — AST lint for PRNG/seed discipline (key reuse
                     without split/fold_in, hardcoded replica seed
                     offsets, numpy calls and tracer branches inside
                     jitted bodies);
- ``fixtures``     — seeded regression fixtures each analyzer must keep
                     flagging (the CLI's --fixture mode).

CLI: ``python scripts/staticcheck.py [--json]`` — wired into tier-1 via
scripts/ci_tier1.sh and tests/test_staticcheck.py. Rule catalogue and
suppression policy: docs/STATIC_ANALYSIS.md.

This module stays import-light (no jax) so engine modules can import the
registry at module import time without cycles or cost.
"""

from p2p_gossip_tpu.staticcheck.registry import (  # noqa: F401
    AuditSpec,
    audited,
    register_entry,
)
