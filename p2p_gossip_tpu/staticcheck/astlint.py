"""AST lint — PRNG/seed discipline and jit-body hygiene.

Pure-``ast`` (no jax import, runs in milliseconds), scanning the package,
``scripts/``, ``bench.py`` and ``__graft_entry__.py``. Tests are out of
scope (they pin seeds on purpose), as is ``staticcheck/fixtures.py``
(deliberately-bad seeded regressions). Rules, catalogued in
docs/STATIC_ANALYSIS.md:

  L1 prng-key-reuse     a ``jax.random.PRNGKey``/``key`` bound to a name
                        and consumed by more than one sampler call
                        without an intervening ``split``/``fold_in``
                        rebind — correlated streams, the classic
                        stateless-PRNG footgun
  L2 seed-offset-literal the replica-derivation constants 104729 / 7919
                        hardcoded anywhere but models/seeds.py — a
                        shadowed copy of the ``seed + r + 104729``
                        contract drifts silently when the canonical one
                        changes, and two call sites disagreeing on the
                        offset makes replica streams collide with solo
                        runs instead of reproducing them
  L3 numpy-in-jit       ``np.*`` / ``numpy.*`` calls inside a
                        jit-decorated function (or a function nested in
                        one): numpy either crashes on tracers or —
                        worse — silently constant-folds a value that was
                        meant to be traced
  L4 tracer-branch      ``if``/``while`` conditions that boolean-test a
                        non-static parameter of a jit-decorated
                        function (``is None`` structure tests and
                        ``.shape``/``.ndim``-style attribute tests are
                        trace-time static and allowed)
"""

from __future__ import annotations

import ast
import dataclasses
import os

from p2p_gossip_tpu.models.seeds import CHURN_SEED_OFFSET, LOSS_SEED_OFFSET

#: The canonical home of the replica seed-offset constants; literal
#: occurrences anywhere else are L2 violations. The values are IMPORTED
#: from that home so this linter never carries a shadow copy itself.
SEEDS_MODULE = os.path.join("p2p_gossip_tpu", "models", "seeds.py")
SEED_OFFSET_LITERALS = {LOSS_SEED_OFFSET, CHURN_SEED_OFFSET}

#: jax.random attrs that do NOT consume a key's uniqueness.
_KEY_SAFE_ATTRS = {
    "split", "fold_in", "key_data", "wrap_key_data", "clone", "key_impl",
}
_KEY_MAKERS = {"PRNGKey", "key"}

#: Files never scanned (relative to the repo root).
EXCLUDE_PARTS = (
    os.path.join("p2p_gossip_tpu", "staticcheck", "fixtures.py"),
    "tests" + os.sep,
)


@dataclasses.dataclass
class LintViolation:
    file: str
    line: int
    rule: str
    message: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"


def _attr_chain(node) -> list[str]:
    """['jax', 'random', 'uniform'] for jax.random.uniform; [] if not a
    plain name/attribute chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return []


def _is_random_call(call: ast.Call) -> list[str]:
    chain = _attr_chain(call.func)
    return chain if "random" in chain[:-1] else []


def _jit_decoration(fn: ast.FunctionDef):
    """(is_jitted, static_names) from the decorator list. Recognizes
    ``@jax.jit``, ``@jit``, and ``@functools.partial(jax.jit, ...)`` /
    ``@partial(jax.jit, ...)`` with literal ``static_argnames``."""
    for deco in fn.decorator_list:
        chain = _attr_chain(deco if not isinstance(deco, ast.Call) else deco.func)
        if chain and chain[-1] == "jit":
            return True, set()
        if isinstance(deco, ast.Call) and chain and chain[-1] == "partial":
            args = deco.args
            if args and _attr_chain(args[0])[-1:] == ["jit"]:
                statics: set[str] = set()
                for kw in deco.keywords:
                    if kw.arg in ("static_argnames", "static_argnums"):
                        for item in ast.walk(kw.value):
                            if isinstance(item, ast.Constant) and isinstance(
                                item.value, str
                            ):
                                statics.add(item.value)
                return True, statics
    return False, set()


def _names_in(node) -> set[str]:
    return {
        n.id for n in ast.walk(node) if isinstance(n, ast.Name)
    }


def _test_flags_param(test, params: set[str]) -> str | None:
    """The offending parameter name if ``test`` boolean-tests one of
    ``params`` in a way that calls ``__bool__`` on a tracer; None if the
    test is trace-time static (``is None``, attribute access, literals)."""
    if isinstance(test, ast.BoolOp):
        for operand in test.values:
            hit = _test_flags_param(operand, params)
            if hit:
                return hit
        return None
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _test_flags_param(test.operand, params)
    if isinstance(test, ast.Compare):
        # `x is None` / `x is not None` are structure tests, never traced.
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return None
        for side in [test.left] + list(test.comparators):
            if isinstance(side, ast.Name) and side.id in params:
                return side.id
        return None
    if isinstance(test, ast.Name) and test.id in params:
        return test.id
    # Attribute tests (x.ndim == 2), calls (isinstance), literals: static
    # at trace time or out of this rule's scope.
    return None


class _FileLinter:
    def __init__(self, path: str, rel: str, tree: ast.Module):
        self.path = path
        self.rel = rel
        self.tree = tree
        self.violations: list[LintViolation] = []

    def flag(self, node, rule: str, message: str) -> None:
        self.violations.append(
            LintViolation(self.rel, getattr(node, "lineno", 0), rule, message)
        )

    # -- L2 ---------------------------------------------------------------
    def lint_seed_literals(self) -> None:
        if self.rel.replace("/", os.sep).endswith(SEEDS_MODULE):
            return
        for node in ast.walk(self.tree):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, int)
                and not isinstance(node.value, bool)
                and node.value in SEED_OFFSET_LITERALS
            ):
                self.flag(
                    node, "seed-offset-literal",
                    f"hardcoded seed offset {node.value} shadows the "
                    "replica-derivation contract — use "
                    "p2p_gossip_tpu.models.seeds "
                    "(loss_stream_seed/churn_stream_seed)",
                )

    # -- L1 ---------------------------------------------------------------
    def lint_key_reuse(self) -> None:
        for fn in ast.walk(self.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._lint_key_reuse_scope(fn)

    def _lint_key_reuse_scope(self, fn) -> None:
        uses: dict[str, int] = {}

        class V(ast.NodeVisitor):
            def visit_FunctionDef(self, node):  # don't cross scopes
                if node is fn:
                    self.generic_visit(node)

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Assign(self, node, outer=self):
                chain = (
                    _is_random_call(node.value)
                    if isinstance(node.value, ast.Call)
                    else []
                )
                for tgt in node.targets:
                    for name_node in ast.walk(tgt):
                        if isinstance(name_node, ast.Name):
                            if chain and chain[-1] in (
                                _KEY_MAKERS | _KEY_SAFE_ATTRS
                            ):
                                # Fresh key or split/fold_in product:
                                # (re)arm the one-use budget.
                                uses[name_node.id] = 0
                            else:
                                # Rebound to something else: stop tracking.
                                uses.pop(name_node.id, None)
                self.generic_visit(node)

            def visit_Call(self, node, outer=self):
                chain = _is_random_call(node)
                if chain and chain[-1] not in (
                    _KEY_SAFE_ATTRS | _KEY_MAKERS
                ):
                    for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        if isinstance(arg, ast.Name) and arg.id in uses:
                            uses[arg.id] += 1
                            if uses[arg.id] > 1:
                                outer.flag(
                                    node, "prng-key-reuse",
                                    f"key '{arg.id}' consumed by more than "
                                    "one sampler without split()/fold_in() "
                                    "— streams are identical, not "
                                    "independent",
                                )
                self.generic_visit(node)

        V().visit(fn)

    # -- L3 / L4 -----------------------------------------------------------
    def lint_jit_bodies(self) -> None:
        for fn in ast.walk(self.tree):
            if not isinstance(fn, ast.FunctionDef):
                continue
            jitted, statics = _jit_decoration(fn)
            if not jitted:
                continue
            params = {
                a.arg
                for a in (
                    fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
                )
            } - statics
            self._lint_jit_body(fn, params)

    def _lint_jit_body(self, fn, traced_params: set[str]) -> None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                chain = _attr_chain(node.func)
                if chain and chain[0] in ("np", "numpy"):
                    self.flag(
                        node, "numpy-in-jit",
                        f"numpy call {'.'.join(chain)}() inside jitted "
                        f"'{fn.name}' — use jnp (numpy crashes on tracers "
                        "or silently constant-folds)",
                    )
            if isinstance(node, (ast.If, ast.While)):
                hit = _test_flags_param(node.test, traced_params)
                if hit:
                    self.flag(
                        node, "tracer-branch",
                        f"Python branch on traced parameter '{hit}' inside "
                        f"jitted '{fn.name}' — trace-time branching needs "
                        "a static arg (static_argnames) or lax.cond/select",
                    )
            if isinstance(node, ast.IfExp):
                hit = _test_flags_param(node.test, traced_params)
                if hit:
                    self.flag(
                        node, "tracer-branch",
                        f"conditional expression on traced parameter "
                        f"'{hit}' inside jitted '{fn.name}' — needs a "
                        "static arg or jnp.where",
                    )


def _scan_roots(repo_root: str) -> list[str]:
    roots = []
    for sub in ("p2p_gossip_tpu", "scripts"):
        base = os.path.join(repo_root, sub)
        for dirpath, _dirs, files in os.walk(base):
            for f in sorted(files):
                if f.endswith(".py"):
                    roots.append(os.path.join(dirpath, f))
    for f in ("bench.py", "__graft_entry__.py"):
        path = os.path.join(repo_root, f)
        if os.path.exists(path):
            roots.append(path)
    return roots


def lint_file(path: str, rel: str | None = None) -> list[LintViolation]:
    rel = rel or path
    with open(path, encoding="utf-8") as f:
        src = f.read()
    return lint_source(src, rel)


def lint_source(src: str, rel: str) -> list[LintViolation]:
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:
        return [LintViolation(rel, e.lineno or 0, "syntax-error", str(e))]
    linter = _FileLinter(rel, rel, tree)
    linter.lint_seed_literals()
    linter.lint_key_reuse()
    linter.lint_jit_bodies()
    return linter.violations


def run_lint(repo_root: str | None = None) -> dict:
    """Lint the repo; JSON-ready {"ok", "files_scanned", "violations"}."""
    if repo_root is None:
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    violations: list[LintViolation] = []
    scanned = 0
    for path in _scan_roots(repo_root):
        rel = os.path.relpath(path, repo_root)
        if any(part in rel + ("" if rel.endswith(".py") else os.sep)
               for part in EXCLUDE_PARTS):
            continue
        scanned += 1
        violations.extend(lint_file(path, rel))
    return {
        "ok": not violations,
        "files_scanned": scanned,
        "violations": [v.as_dict() for v in violations],
    }
