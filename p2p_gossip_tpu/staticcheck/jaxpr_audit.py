"""Jaxpr invariant auditor — abstract-trace every registered entry point.

The compiled stack's correctness rests on invariants the type system
never sees: jax runs with x64 disabled so a 64-bit dtype in a jaxpr
means someone flipped the flag (and every uint32 counter-hash coin now
computes different bits); the tick kernels are pure integer/bitwise
programs, so any inexact dtype is a weak-type promotion silently
upcasting counter chains; host callbacks and device transfers inside a
kernel serialize the while-loop on the host; and the bitmask packing
contract (slot s at word s // 32 — ops/bitmask.py) fixes the minor axis
of every uint32 buffer, so a mismatched word count silently maps slots
into a different share universe.

``jax.make_jaxpr`` traces each registered entry on its AuditSpec's tiny
operands (no execution, no device work, sub-second per entry) and the
walker below visits every equation including nested sub-jaxprs (pjit,
while, scan, cond, shard_map). Rules, catalogued in
docs/STATIC_ANALYSIS.md:

  J1 forbid-64bit      int64/uint64/float64/complex128 anywhere
  J2 integer-only      inexact dtypes in entries marked integer_only
  J3 no-host-callback  debug_callback / pure_callback / io_callback /
                       debug_print / callback primitives
  J4 no-device-put     device_put primitives (implicit transfers)
  J5 static-shapes     every dimension a concrete int
  J6 bitmask-words     uint32 arrays of rank >= 2 in the entry's own
                       signature must pack their minor axis to a declared
                       word width (internal uint32 arrays are exempt —
                       the counter-hash coins and bit-position math
                       legitimately carry uint32 at other widths)
"""

from __future__ import annotations

import dataclasses
import traceback

FORBIDDEN_64BIT = {"int64", "uint64", "float64", "complex128"}
HOST_CALLBACK_PRIMITIVES = {
    "debug_callback", "pure_callback", "io_callback", "callback",
    "debug_print",
}
TRANSFER_PRIMITIVES = {"device_put"}


@dataclasses.dataclass
class Violation:
    entry: str
    rule: str
    message: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:  # human report line
        return f"{self.entry}: [{self.rule}] {self.message}"


def _jaxpr_classes():
    from jax.extend import core as jex_core

    return (jex_core.Jaxpr, jex_core.ClosedJaxpr)


def iter_eqns(jaxpr):
    """Depth-first over every equation including sub-jaxprs nested in
    params (pjit/while/scan/cond/shard_map/custom_* all stash theirs
    there, in varying containers)."""
    jaxpr_cls, closed_cls = _jaxpr_classes()

    def walk(j):
        if isinstance(j, closed_cls):
            j = j.jaxpr
        for eqn in j.eqns:
            yield eqn
            for val in eqn.params.values():
                for sub in _subjaxprs(val):
                    yield from walk(sub)

    def _subjaxprs(val):
        if isinstance(val, (jaxpr_cls, closed_cls)):
            yield val
        elif isinstance(val, (tuple, list)):
            for item in val:
                yield from _subjaxprs(item)

    yield from walk(jaxpr)


def _avals_of(jaxpr):
    """Every abstract value in the jaxpr: top-level binders plus each
    equation's operands and results (literals included — a 64-bit
    constant is as much a violation as a 64-bit operand)."""
    seen = []
    for v in list(jaxpr.jaxpr.invars) + list(jaxpr.jaxpr.outvars):
        aval = getattr(v, "aval", None)
        if aval is not None:
            seen.append(aval)
    for eqn in iter_eqns(jaxpr):
        for v in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(v, "aval", None)
            if aval is not None:
                seen.append(aval)
    return seen


def _signature_avals(jaxpr):
    """The entry's own inputs and outputs (the caller-visible contract)."""
    out = []
    for v in list(jaxpr.jaxpr.invars) + list(jaxpr.jaxpr.outvars):
        aval = getattr(v, "aval", None)
        if aval is not None:
            out.append(aval)
    return out


def audit_entry(entry) -> list[Violation]:
    """Trace one registry entry and apply rules J1-J6."""
    import jax

    violations: list[Violation] = []
    try:
        spec = entry.spec()
    except Exception:
        return [Violation(
            entry.name, "spec-error",
            f"audit spec failed to build:\n{traceback.format_exc(limit=4)}",
        )]
    fn = spec.fn if spec.fn is not None else entry.fn
    if fn is None:
        return [Violation(
            entry.name, "spec-error", "no callable registered or built"
        )]
    try:
        closed = jax.make_jaxpr(
            lambda *args: fn(*args, **spec.kwargs)
        )(*spec.args)
    except Exception:
        return [Violation(
            entry.name, "trace-error",
            f"abstract trace failed:\n{traceback.format_exc(limit=4)}",
        )]

    avals = _avals_of(closed)

    # J1 / J2 / J5 — dtype and shape discipline over every aval.
    flagged_dtypes = set()
    for aval in avals:
        dtype = getattr(aval, "dtype", None)
        shape = getattr(aval, "shape", ())
        if dtype is not None:
            name = str(dtype)
            if name in FORBIDDEN_64BIT and name not in flagged_dtypes:
                flagged_dtypes.add(name)
                violations.append(Violation(
                    entry.name, "forbid-64bit",
                    f"{name} value of shape {tuple(shape)} in traced "
                    "graph — x64 must stay off (uint32 counter-hash "
                    "coins change bits under x64)",
                ))
            if (
                spec.integer_only
                and name.startswith(("float", "bfloat", "complex"))
                and name not in flagged_dtypes
            ):
                flagged_dtypes.add(name)
                violations.append(Violation(
                    entry.name, "integer-only",
                    f"inexact dtype {name} (shape {tuple(shape)}) in an "
                    "integer/bitwise kernel — weak-type promotion from a "
                    "stray Python float?",
                ))
        for dim in shape:
            if not isinstance(dim, int):
                violations.append(Violation(
                    entry.name, "static-shapes",
                    f"non-static dimension {dim!r} in shape "
                    f"{tuple(shape)} — every XLA compilation must see "
                    "static shapes",
                ))
                break

    # J3 / J4 — forbidden primitives.
    flagged_prims = set()
    for eqn in iter_eqns(closed):
        name = eqn.primitive.name
        if name in HOST_CALLBACK_PRIMITIVES and name not in flagged_prims:
            flagged_prims.add(name)
            violations.append(Violation(
                entry.name, "no-host-callback",
                f"host callback primitive '{name}' — a callback inside a "
                "compiled tick loop serializes every iteration on the host",
            ))
        if name in TRANSFER_PRIMITIVES and name not in flagged_prims:
            flagged_prims.add(name)
            violations.append(Violation(
                entry.name, "no-device-put",
                f"'{name}' inside the traced graph — stage operands "
                "before the jit boundary, not per call",
            ))

    # J6 — bitmask word-width contract (signature avals only).
    if spec.bitmask_words is not None:
        allowed = spec.bitmask_words
        if isinstance(allowed, int):
            allowed = (allowed,)
        allowed = set(allowed)
        bad = set()
        for aval in _signature_avals(closed):
            dtype = getattr(aval, "dtype", None)
            shape = tuple(getattr(aval, "shape", ()))
            if (
                dtype is not None
                and str(dtype) == "uint32"
                and len(shape) >= 2
                and shape[-1] not in allowed
                and shape not in bad
            ):
                bad.add(shape)
                violations.append(Violation(
                    entry.name, "bitmask-words",
                    f"uint32 array of shape {shape} packs its minor axis "
                    f"to {shape[-1]} words; this entry's declared word "
                    f"widths are {sorted(allowed)} "
                    "(ops/bitmask.py packing contract: slot s lives at "
                    "word s // 32)",
                ))
    return violations


def run_audit(entries=None) -> dict:
    """Audit every registered entry. Returns a JSON-ready report:
    {"ok", "entries_audited", "entries", "violations": [...]}. Importing
    the registry's population list is the caller's job only when a
    custom ``entries`` iterable is NOT given."""
    if entries is None:
        from p2p_gossip_tpu.staticcheck import entrypoints, registry

        entrypoints.load_all()
        entries = registry.all_entries()
    violations: list[Violation] = []
    names = []
    for entry in entries:
        names.append(entry.name)
        violations.extend(audit_entry(entry))
    return {
        "ok": not violations,
        "entries_audited": len(names),
        "entries": names,
        "violations": [v.as_dict() for v in violations],
    }
