"""Recompile sentinel — one compilation per distinct sweep-grid signature.

The campaign engine's whole throughput story rests on "one compile
serves every cell that shares shapes" (batch/sweep.py): a weak-type
drift (int64 seed array where the kernel saw uint32), a pad-width
wobble, or a static arg that silently varies per cell multiplies the
sweep's wall by the compile cost — and on a TPU tunnel window, burns
the window. Nothing caught that before: XLA recompiles silently.

The sentinel replays a small grid through the REAL sweep runner
(`batch.sweep.run_sweep`) and counts jit-cache misses on the registered
campaign kernels (the ``count_compiles`` entries in the staticcheck
registry — jit wrappers expose ``_cache_size``). The expected count per
kernel is computed from the grid spec by the same static-signature
rules the kernels declare (`expected_compiles`); measured != expected
fails, in either direction — an over-count is a recompile leak, an
under-count means the expectation model drifted from the kernels and
must be fixed here, not suppressed.

``jax.clear_caches()`` runs before the replay so prior compilations in
the process (tests, earlier stages) can't mask a miss.
"""

from __future__ import annotations

import dataclasses


#: Kernel-name aliases: registry entry name -> short report key.
_KERNELS = {
    "batch.campaign._run_coverage_batch": "coverage_batch",
    "batch.campaign._run_while_batch": "while_batch",
    "models.protocols._run_pushpull_replicas": "pushpull_replicas",
    "models.protocols._run_pushk_replicas": "pushk_replicas",
}


def default_grid() -> dict:
    """The shipped replay grid: 6 cells spanning the flood campaign and
    the batched Demers trio, with a loss axis (static threshold — each
    distinct lossProb is one legitimate compile) — small enough for
    tier-1 (~4 s on CPU) while exercising every counted kernel."""
    return {
        "numNodes": 64,
        "p": 0.1,
        "shares": 2,
        "horizon": 16,
        "replicas": 4,
        "protocol": ["push", "pushpull", "pushk"],
        "fanout": [2],
        "lossProb": [0.0, 0.1],
    }


def expected_compiles(spec: dict) -> dict[str, int]:
    """Distinct compile signatures per counted kernel for ``spec``.

    Mirrors the static/shape config the sweep path derives per cell:
    the kernel a protocol routes to, the graph knob ``p`` (changes ELL
    operand shapes), the loss THRESHOLD (static in every kernel; the
    flood path also bakes the seed — both derive from lossProb/baseSeed),
    churn presence (changes the operand pytree structure), fanout
    (static, pushk only), the anti-entropy mode, and the shared scalar
    shape knobs. A signature set per kernel; the expected count is its
    size. If a kernel gains a new static arg that varies per cell, add
    it HERE — the sentinel failing "under-compiled expectation" is the
    reminder."""
    from p2p_gossip_tpu.batch.sweep import expand_grid

    sigs: dict[str, set] = {k: set() for k in _KERNELS.values()}
    for cell in expand_grid(spec):
        shape_sig = (
            cell["numNodes"], cell["p"], cell["shares"], cell["horizon"],
            _replica_count(cell), cell["baseSeed"],
        )
        loss_sig = cell["lossProb"]
        churn_sig = cell["churnProb"] > 0.0
        if cell["protocol"] == "push":
            sigs["coverage_batch"].add((shape_sig, loss_sig, churn_sig))
        elif cell["protocol"] in ("pushpull", "pull"):
            sigs["pushpull_replicas"].add(
                (shape_sig, loss_sig, churn_sig, cell["protocol"])
            )
        elif cell["protocol"] == "pushk":
            sigs["pushk_replicas"].add(
                (shape_sig, loss_sig, churn_sig, cell["fanout"])
            )
    return {k: len(v) for k, v in sigs.items()}


def _replica_count(cell) -> int:
    reps = cell["replicas"]
    return len(reps) if isinstance(reps, list) else int(reps)


def _counted_kernels() -> dict[str, object]:
    from p2p_gossip_tpu.staticcheck import entrypoints, registry

    entrypoints.load_all()
    out = {}
    for entry in registry.countable_entries():
        key = _KERNELS.get(entry.name, entry.name)
        out[key] = entry.jit_target()
    return out


@dataclasses.dataclass
class SentinelReport:
    ok: bool
    expected: dict[str, int]
    measured: dict[str, int]
    cells: int

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def violations(self) -> list[str]:
        out = []
        for k in sorted(set(self.expected) | set(self.measured)):
            e, m = self.expected.get(k, 0), self.measured.get(k, 0)
            if m > e:
                out.append(
                    f"recompile-sentinel: kernel '{k}' compiled {m}x for "
                    f"{e} distinct grid signature(s) — a static arg or "
                    "operand shape/dtype drifts between calls that should "
                    "share one executable"
                )
            elif m < e:
                out.append(
                    f"recompile-sentinel: kernel '{k}' compiled {m}x but "
                    f"the grid model expected {e} — expected_compiles() "
                    "drifted from the kernels; fix the model"
                )
        return out


def run_sentinel(spec: dict | None = None) -> SentinelReport:
    """Clear jit caches, replay ``spec`` through the real sweep runner,
    and compare per-kernel cache sizes against ``expected_compiles``."""
    import jax

    from p2p_gossip_tpu.batch.sweep import expand_grid, run_sweep

    if spec is None:
        spec = default_grid()
    kernels = _counted_kernels()
    expected = expected_compiles(spec)
    jax.clear_caches()
    run_sweep(spec)
    measured = {
        name: int(fn._cache_size()) for name, fn in kernels.items()
    }
    ok = all(
        measured.get(k, 0) == expected.get(k, 0)
        for k in set(expected) | set(measured)
    )
    return SentinelReport(
        ok=ok, expected=expected, measured=measured,
        cells=len(expand_grid(spec)),
    )


def measure_compiles(fn_or_name):
    """Current cache size of a counted kernel (test helper)."""
    kernels = _counted_kernels()
    if isinstance(fn_or_name, str):
        return int(kernels[fn_or_name]._cache_size())
    return int(fn_or_name._cache_size())


# --- serve-trace sentinel (serve/scheduler.py's one-compile promise) -------

def default_serve_trace() -> list[dict]:
    """The shipped mixed request trace: 2 topologies x 3 protocols x
    mixed replica counts, several requests sharing each signature so the
    replay exercises cross-request slot packing, plus a loss variant
    (one extra legitimate flood compile). Small enough for tier-1."""
    er = {"family": "erdos_renyi", "n": 64, "p": 0.1, "seed": 1}
    ws = {"family": "watts_strogatz", "n": 64, "k": 4, "beta": 0.1,
          "seed": 2}
    base = {"shares": 2, "horizon": 12}
    reqs = [
        {"topology": er, "protocol": "flood", "seeds": [0, 1, 2]},
        {"topology": er, "protocol": "flood", "seeds": [3, 4]},
        {"topology": ws, "protocol": "flood", "seeds": [5]},
        {"topology": ws, "protocol": "flood", "seeds": [6, 7, 8]},
        {"topology": er, "protocol": "pushpull", "seeds": [9, 10]},
        {"topology": er, "protocol": "pushpull", "seeds": [11]},
        {"topology": ws, "protocol": "pushk", "seeds": [12, 13]},
        {"topology": er, "protocol": "flood", "seeds": [14, 15],
         "loss_prob": 0.1},
    ]
    return [
        {"request_id": f"sentinel-{i}", **base, **r}
        for i, r in enumerate(reqs)
    ]


def _serve_compile_sig(server, req) -> tuple:
    """The jit-cache signature a request's dispatches hit: the kernel it
    routes to, the DeviceGraph's pytree structure + leaf shapes/dtypes
    (exactly what jax's cache keys on for the traced operands), the
    batch width, and every static argument the campaign runner derives
    from the request. Mirrors batch/campaign.py's derivations — if a
    campaign kernel gains a per-request static arg, add it HERE (the
    sentinel failing "under-compiled expectation" is the reminder)."""
    import jax

    from p2p_gossip_tpu.engine.sync import MIN_CHUNK_SHARES, _resolve_block
    from p2p_gossip_tpu.ops import bitmask

    dg = server._device_graph(req)
    leaves, treedef = jax.tree_util.tree_flatten(dg)
    dg_sig = (
        str(treedef),
        tuple((tuple(l.shape), str(l.dtype)) for l in leaves),
    )
    on_tpu = any(d.platform == "tpu" for d in dg.ell_idx.devices())
    s = int(req.shares)
    thr = int(round(float(req.loss_prob) * (1 << 32)))
    loss_on = req.loss_prob > 0.0
    churn_on = req.churn_prob > 0.0
    b = server.slots
    # The exchange mode is a static arg of the SHARDED campaign runners
    # only — single-device dispatches ignore it, so folding it into
    # their signatures would over-count expected compiles.
    exchange = (
        (getattr(req, "exchange", "auto")
         if getattr(req, "exchange", "auto") != "auto"
         else server.exchange)
        if server.mesh is not None else None
    )
    if req.protocol == "flood":
        floor = MIN_CHUNK_SHARES if on_tpu else min(MIN_CHUNK_SHARES, 128)
        chunk = bitmask.num_words(max(s, floor)) * bitmask.WORD_BITS
        block = _resolve_block(dg, None)
        return (
            "coverage_batch", dg_sig, b, chunk, int(req.horizon), block,
            (thr, None) if loss_on else None, loss_on, churn_on, s,
            exchange,
        )
    if on_tpu:
        chunk_size = MIN_CHUNK_SHARES
    else:
        chunk_size = min(max(s, 1), min(MIN_CHUNK_SHARES, 128))
    chunk = bitmask.num_words(max(chunk_size, 1)) * bitmask.WORD_BITS
    common = (dg_sig, b, chunk, int(req.horizon), thr, churn_on, exchange)
    if req.protocol == "pushk":
        return ("pushk_replicas",) + common + (int(req.fanout),)
    return ("pushpull_replicas",) + common + (req.protocol,)


def expected_serve_compiles(requests, server) -> dict[str, int]:
    """Distinct compile signatures per counted kernel for a request
    trace served at ``server``'s slot width."""
    sigs: dict[str, set] = {k: set() for k in _KERNELS.values()}
    for req in requests:
        sig = _serve_compile_sig(server, req)
        sigs[sig[0]].add(sig[1:])
    return {k: len(v) for k, v in sigs.items()}


def run_serve_sentinel(trace: list[dict] | None = None) -> SentinelReport:
    """Replay a mixed request trace through the serving scheduler and
    fail if any counted campaign kernel compiled more than once per
    distinct static signature — the continuous-batching premise that
    backfilled slots reuse already-compiled programs. Like
    `run_sentinel`, an under-count also fails (the expectation model
    drifted)."""
    import jax

    from p2p_gossip_tpu.serve.request import SimRequest
    from p2p_gossip_tpu.serve.server import GossipServer

    if trace is None:
        trace = default_serve_trace()
    requests = [SimRequest.from_dict(d) for d in trace]
    kernels = _counted_kernels()
    server = GossipServer(slots=4)
    expected = expected_serve_compiles(requests, server)
    jax.clear_caches()
    for req in requests:
        server.submit(req)
    server.drain()
    measured = {
        name: int(fn._cache_size()) for name, fn in kernels.items()
    }
    ok = all(
        measured.get(k, 0) == expected.get(k, 0)
        for k in set(expected) | set(measured)
    )
    return SentinelReport(
        ok=ok, expected=expected, measured=measured, cells=len(requests),
    )
