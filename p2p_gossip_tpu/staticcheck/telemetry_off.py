"""Telemetry zero-cost checker — "off" must mean OFF, in the jaxpr.

The telemetry layer's claim is that disabled runs pay nothing: the
metric rings are gated by a static ``telemetry`` flag, and with the flag
down the traced program is byte-identical to the pre-telemetry kernel.
That claim is enforced here, not asserted in a docstring:

  T1 no-ring-when-off   the telemetry-OFF trace of every instrumented
                        entry contains no metric-ring aval anywhere —
                        no uint32 array whose minor axis is NUM_METRICS
                        (the ring's signature shape) at rank >= 2.
  T2 flag-gates         the telemetry-ON trace differs from the OFF
                        trace (the flag actually instruments — a flag
                        that became a no-op would silently kill the
                        subsystem while every test still passed).
  T3 default-is-off     for directly-jitted kernels, tracing with
                        ``telemetry=False`` passed explicitly yields a
                        string-identical jaxpr to the default call —
                        existing call sites (which pass nothing) are on
                        the off path.
  T4 no-digest-when-off the OFF trace contains no state-digest math.
                        The digest ring is rank-1 (no shape signature to
                        scan for), so the rule greps the trace for the
                        digest's mix constants (telemetry/digest.py
                        keeps them unique in the codebase — lowbias32,
                        not the murmur3 family the counter-hash coins
                        use), both as inline literals in the jaxpr text
                        and as hoisted scalar uint32 consts.

Instrumented surfaces are discovered from the audit registry by naming
convention: every ``<name>[telemetry]`` entry is the ON form of
``<name>``. A new instrumented kernel that registers its pair is checked
automatically.

The ``telemetry`` regression fixture (scripts/staticcheck.py --fixture
telemetry) forces the rings on via `telemetry.rings._FIXTURE_FORCE` and
asserts T1 flags it — proving the checker still catches an always-on
ring. The ``digest`` fixture does the same through
`telemetry.digest._FIXTURE_FORCE` for T4.
"""

from __future__ import annotations

import re
import traceback

from p2p_gossip_tpu.staticcheck.jaxpr_audit import Violation, _avals_of
from p2p_gossip_tpu.telemetry.schema import NUM_METRICS

TELEMETRY_SUFFIX = "[telemetry]"


def _trace(fn, args, kwargs):
    import jax

    return jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)


def _ring_avals(closed) -> list[tuple]:
    """Shapes of metric-ring-like avals: uint32, rank >= 2, minor axis
    exactly NUM_METRICS — the ring's unmistakable signature (bitmask
    word widths are powers of two >= 1, delta capacities and the
    exchange-counter row are multiples of 8; NUM_METRICS is 9 and must
    stay odd so no kernel array can alias it)."""
    found = []
    for aval in _avals_of(closed):
        dtype = getattr(aval, "dtype", None)
        shape = tuple(getattr(aval, "shape", ()))
        if (
            dtype is not None
            and str(dtype) == "uint32"
            and len(shape) >= 2
            and shape[-1] == NUM_METRICS
            and shape not in found
        ):
            found.append(shape)
    return found


def _digest_leaks(closed) -> list[str]:
    """Evidence of digest math in a trace: the mix constants, inline in
    the jaxpr text or hoisted into scalar uint32 consts. Word-boundary
    match — the decimal digits must form a whole literal."""
    import numpy as np

    from p2p_gossip_tpu.telemetry.digest import MIX_M1, MIX_M2

    found = []
    text = str(closed)
    for c in (MIX_M1, MIX_M2):
        if re.search(rf"\b{c}\b", text):
            found.append(f"inline literal {c} (0x{c:08X})")
    for cv in getattr(closed, "consts", ()):
        try:
            arr = np.asarray(cv)
        except Exception:
            continue
        if (
            arr.dtype == np.uint32
            and arr.ndim == 0
            and int(arr) in (MIX_M1, MIX_M2)
        ):
            found.append(f"hoisted uint32 const {int(arr)}")
    return found


def telemetry_pairs(entries=None):
    """(base_entry, on_entry) pairs from the registry's naming
    convention. ``entries`` overrides the registry for tests."""
    if entries is None:
        from p2p_gossip_tpu.staticcheck import entrypoints, registry

        entrypoints.load_all()
        entries = registry.all_entries()
    by_name = {e.name: e for e in entries}
    pairs = []
    for name, entry in sorted(by_name.items()):
        if name.endswith(TELEMETRY_SUFFIX):
            base = by_name.get(name[: -len(TELEMETRY_SUFFIX)])
            if base is not None:
                pairs.append((base, entry))
    return pairs


def check_pair(base, on_entry) -> list[Violation]:
    """Apply T1-T3 to one (off, on) entry pair."""
    violations: list[Violation] = []
    try:
        base_spec = base.spec()
        on_spec = on_entry.spec()
    except Exception:
        return [Violation(
            on_entry.name, "spec-error",
            f"telemetry spec failed to build:\n"
            f"{traceback.format_exc(limit=4)}",
        )]
    base_fn = base_spec.fn if base_spec.fn is not None else base.fn
    on_fn = on_spec.fn if on_spec.fn is not None else on_entry.fn
    try:
        off_jaxpr = _trace(base_fn, base_spec.args, base_spec.kwargs)
        on_jaxpr = _trace(on_fn, on_spec.args, on_spec.kwargs)
    except Exception:
        return [Violation(
            on_entry.name, "trace-error",
            f"telemetry trace failed:\n{traceback.format_exc(limit=4)}",
        )]

    # T1 — the off program carries no ring.
    rings_off = _ring_avals(off_jaxpr)
    if rings_off:
        violations.append(Violation(
            base.name, "telemetry-off-clean",
            f"telemetry-OFF trace carries metric-ring avals {rings_off} — "
            "the rings must compile away when disabled (zero-cost "
            "contract, docs/OBSERVABILITY.md)",
        ))

    # T4 — the off program carries no digest math.
    leaks = _digest_leaks(off_jaxpr)
    if leaks:
        violations.append(Violation(
            base.name, "digest-off-clean",
            f"telemetry-OFF trace contains digest mix constants "
            f"({'; '.join(leaks)}) — the state-digest ring must compile "
            "away when disabled (zero-cost contract, "
            "docs/OBSERVABILITY.md)",
        ))

    # T2 — the flag actually instruments.
    if str(on_jaxpr) == str(off_jaxpr):
        violations.append(Violation(
            on_entry.name, "telemetry-flag-gates",
            "telemetry-ON trace is identical to the OFF trace — the "
            "static flag no longer instruments anything",
        ))

    # T3 — explicit False == default, for directly-jitted kernels whose
    # spec kwargs we can extend (factory-built runners bake the flag at
    # build time, where default-off holds by construction).
    if base_spec.fn is None and base.fn is not None:
        try:
            explicit = _trace(
                base.fn, base_spec.args,
                {**base_spec.kwargs, "telemetry": False},
            )
            if str(explicit) != str(off_jaxpr):
                violations.append(Violation(
                    base.name, "telemetry-default-off",
                    "telemetry=False traces differently from the default "
                    "call — existing call sites are not on the off path",
                ))
        except Exception:
            violations.append(Violation(
                base.name, "trace-error",
                f"telemetry=False trace failed:\n"
                f"{traceback.format_exc(limit=4)}",
            ))
    return violations


def run_telemetry_check(entries=None, only=None) -> dict:
    """Check every registered telemetry pair. ``only`` (iterable of base
    names) restricts the sweep — the fixture checks one pair."""
    pairs = telemetry_pairs(entries)
    if only is not None:
        keep = set(only)
        pairs = [(b, o) for b, o in pairs if b.name in keep]
    violations: list[Violation] = []
    names = []
    for base, on_entry in pairs:
        names.append(base.name)
        violations.extend(check_pair(base, on_entry))
    return {
        "ok": not violations,
        "pairs_checked": len(names),
        "entries": names,
        "violations": [v.as_dict() for v in violations],
    }
