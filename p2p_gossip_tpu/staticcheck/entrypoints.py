"""Populate the audit registry: import every module that registers.

The registry is filled by import side effects (the ``@audited`` decorator
and module-bottom ``register_entry`` calls), so the auditor needs the
registering modules imported first. This module is that one list; a new
engine module added here — or imported by anything here — is audited by
default from then on.
"""

from __future__ import annotations


def load_all() -> None:
    """Import every registering module (idempotent)."""
    import p2p_gossip_tpu.batch.campaign  # noqa: F401
    import p2p_gossip_tpu.engine.sync  # noqa: F401
    import p2p_gossip_tpu.models.protocols  # noqa: F401
    import p2p_gossip_tpu.ops.bitmask  # noqa: F401
    import p2p_gossip_tpu.ops.ell  # noqa: F401
    import p2p_gossip_tpu.ops.segment  # noqa: F401
    import p2p_gossip_tpu.parallel.engine_sharded  # noqa: F401
    import p2p_gossip_tpu.parallel.exchange  # noqa: F401
    import p2p_gossip_tpu.parallel.protocols_sharded  # noqa: F401
