"""Seeded regression fixtures — deliberately-bad inputs each analyzer
MUST keep flagging.

The analyzers gate tier-1; a refactor that silently blinds one of them
would leave the gate green while the guardrail is gone. Each fixture
here reproduces one historical failure mode on tiny shapes; the CLI's
``--fixture`` mode runs one and exits non-zero iff the analyzer still
flags it (tests/test_staticcheck.py asserts all three, and that the
shipped tree stays clean). This file is excluded from the AST lint scan
(astlint.EXCLUDE_PARTS) — it is bad on purpose.

Fixtures:

  f64        a "kernel" whose integer math weak-promotes through a
             Python float and lands on float64 under x64 — the dtype
             drift that changes every uint32 counter-hash coin
  recompile  one campaign cell run twice with a drifted replica batch
             size — the shape wobble that burns a sweep's compile
             budget (and a tunnel window) silently
  prng       jax.random key consumed by two samplers without split() —
             correlated streams masquerading as independent replicas
  telemetry  metric rings forced on with the telemetry flag down — the
             always-on instrumentation that would silently break the
             zero-cost contract (telemetry_off.py must flag the ring
             avals in the supposedly-off trace)
  digest     state-digest ring forced on with the telemetry flag down —
             same zero-cost contract, separate detection channel: the
             digest ring is rank-1, so telemetry_off.py's T4 rule greps
             the OFF trace for the digest mix constants instead of
             scanning aval shapes
  exchange   a delta-exchange compaction whose rank/keep computation
             drifts through float32 — past ~2^24 cut rows the mantissa
             rounds the cumsum and a capacity-C buffer silently keeps
             the wrong words; the integer-only audit (J2) must flag the
             inexact avals
  async      a bounded-staleness accounting step (the async exchange's
             ``staleness`` telemetry column) whose late-fold tally
             drifts through float32 — past ~2^24 accumulated stale
             word-folds the column silently saturates low and the
             staleness <= (K-1) * stale_folds bound reads as satisfied
             when it is not; the integer-only audit (J2, same
             discipline as the real ``flood_runner[async]`` entries)
             must flag the inexact avals
"""

from __future__ import annotations

FIXTURES = (
    "f64", "recompile", "prng", "telemetry", "digest", "exchange",
    "meshfact", "async", "hub",
)


def f64_fixture() -> dict:
    """Trace a bad integer kernel under x64 and audit it: the auditor
    must report forbid-64bit (and integer-only) violations."""
    import jax.numpy as jnp
    from jax.experimental import enable_x64

    from p2p_gossip_tpu.staticcheck.jaxpr_audit import audit_entry
    from p2p_gossip_tpu.staticcheck.registry import AuditEntry, AuditSpec

    def bad_tick_update(seen):
        # The classic weak-type leak: a Python float in bitmask counter
        # math. Under x64 the promotion lands on float64.
        scaled = seen.astype(jnp.int64) * 2.0
        return scaled.sum()

    def spec():
        return AuditSpec(
            args=(jnp.zeros((4, 2), dtype=jnp.uint32),),
            integer_only=True,
        )

    entry = AuditEntry(
        name="fixtures.f64_bad_tick_update", fn=bad_tick_update, spec=spec
    )
    with enable_x64():
        violations = audit_entry(entry)
    return {
        "fixture": "f64",
        "ok": not violations,  # must come back False
        "violations": [v.as_dict() for v in violations],
    }


def recompile_fixture() -> dict:
    """Run one campaign cell twice with a drifted batch size: the
    sentinel's cache counter must see two executables where the cell's
    signature model allows one."""
    import jax

    from p2p_gossip_tpu.batch.campaign import (
        _run_coverage_batch,
        flood_replicas,
        run_coverage_campaign,
    )
    from p2p_gossip_tpu.models.topology import erdos_renyi
    from p2p_gossip_tpu.staticcheck.recompile import SentinelReport

    graph = erdos_renyi(48, 0.15, seed=0)
    replicas = flood_replicas(graph, 2, [0, 1, 2, 3], 16)
    jax.clear_caches()
    run_coverage_campaign(graph, replicas, 16)
    # The deliberate shape drift: same cell, replica batch halved — the
    # (B, ...) leading axis changes and XLA compiles a second program.
    run_coverage_campaign(graph, replicas, 16, batch_size=2)
    expected = {"coverage_batch": 1}
    measured = {"coverage_batch": int(_run_coverage_batch._cache_size())}
    report = SentinelReport(
        ok=measured == expected, expected=expected, measured=measured,
        cells=1,
    )
    return {
        "fixture": "recompile",
        "ok": report.ok,  # must come back False
        "violations": [{"rule": "recompile-sentinel", "message": m}
                       for m in report.violations()],
        "expected": expected,
        "measured": measured,
    }


_PRNG_BAD_SOURCE = '''\
import jax


def sample_two_replicas(seed):
    key = jax.random.PRNGKey(seed)
    a = jax.random.uniform(key, (8,))
    b = jax.random.normal(key, (8,))  # same key: b correlates with a
    return a, b
'''


def prng_fixture() -> dict:
    """Lint a snippet that reuses a PRNG key across two samplers: the
    AST lint must report prng-key-reuse."""
    from p2p_gossip_tpu.staticcheck.astlint import lint_source

    violations = lint_source(_PRNG_BAD_SOURCE, "fixtures/prng_bad.py")
    flagged = [v for v in violations if v.rule == "prng-key-reuse"]
    return {
        "fixture": "prng",
        "ok": not flagged,  # must come back False
        "violations": [v.as_dict() for v in flagged],
    }


def telemetry_fixture() -> dict:
    """Force the metric rings on while the telemetry flag is down (the
    `rings._FIXTURE_FORCE` backdoor) and run the zero-cost check on one
    instrumented kernel: the checker must flag ring avals in the
    telemetry-OFF trace."""
    import jax

    from p2p_gossip_tpu.staticcheck.telemetry_off import run_telemetry_check
    from p2p_gossip_tpu.telemetry import rings

    rings._FIXTURE_FORCE = True
    # Cache discipline matters on BOTH edges: a pre-existing pjit trace
    # of the kernel would satisfy make_jaxpr without re-running the
    # (now-forced) trace-time gate, hiding the seeded bug; and a trace
    # taken while forced would poison the cache for later legitimate
    # telemetry=False calls.
    jax.clear_caches()
    try:
        report = run_telemetry_check(only=("engine.sync._run_chunk_while",))
    finally:
        rings._FIXTURE_FORCE = False
        jax.clear_caches()
    return {
        "fixture": "telemetry",
        "ok": report["ok"],  # must come back False
        "violations": report["violations"],
    }


def digest_fixture() -> dict:
    """Force the state-digest ring on while the telemetry flag is down
    (the `digest._FIXTURE_FORCE` backdoor) and run the zero-cost check on
    one instrumented kernel: the T4 rule must find the digest mix
    constants in the telemetry-OFF trace."""
    import jax

    from p2p_gossip_tpu.staticcheck.telemetry_off import run_telemetry_check
    from p2p_gossip_tpu.telemetry import digest

    digest._FIXTURE_FORCE = True
    # Same cache discipline as telemetry_fixture, both edges.
    jax.clear_caches()
    try:
        report = run_telemetry_check(only=("engine.sync._run_chunk_while",))
    finally:
        digest._FIXTURE_FORCE = False
        jax.clear_caches()
    return {
        "fixture": "digest",
        "ok": report["ok"],  # must come back False
        "violations": report["violations"],
    }


def exchange_fixture() -> dict:
    """Audit a deliberately-bad frontier-delta compaction: the write-side
    rank computation (which of a shard's changed bitmask words fit the
    fixed-capacity buffer) drifts through float32, the dtype leak that
    would silently drop the wrong words once the cut-row count passes
    the 2^24 mantissa. The integer-only audit (J2, same discipline the
    real ``parallel.exchange.compress_deltas`` entry is registered
    under) must flag the inexact avals."""
    import jax.numpy as jnp

    from p2p_gossip_tpu.staticcheck.jaxpr_audit import audit_entry
    from p2p_gossip_tpu.staticcheck.registry import AuditEntry, AuditSpec

    def bad_compress_deltas(changed, need):
        # The seeded bug: per-row ranks via a float32 cumsum. Exact only
        # below 2^24 rows — beyond it, equal ranks collide and the
        # capacity cut keeps a wrong subset, bitwise-silently.
        changed_rows = (changed != 0).any(axis=1) & need[:, 0]
        ranks = jnp.cumsum(changed_rows.astype(jnp.float32))
        keep = changed_rows & (ranks <= 8.0)
        return jnp.where(keep[:, None], changed, jnp.uint32(0))

    def spec():
        return AuditSpec(
            args=(
                jnp.zeros((16, 2), dtype=jnp.uint32),
                jnp.zeros((16, 1), dtype=jnp.bool_),
            ),
            integer_only=True,
        )

    entry = AuditEntry(
        name="fixtures.exchange_bad_compress_deltas",
        fn=bad_compress_deltas, spec=spec,
    )
    violations = audit_entry(entry)
    return {
        "fixture": "exchange",
        "ok": not violations,  # must come back False
        "violations": [v.as_dict() for v in violations],
    }


def hub_fixture() -> dict:
    """Audit a deliberately-bad hub overlay: the flat row ids the
    all_gathered hub block scatters back onto the reconstruction canvas
    (shard offset + local hub row) computed through float32 — exact
    only below 2^24 rows, beyond which two distinct hub rows round to
    one flat id and the overlay drops a hub's words, bitwise-silently.
    The integer-only audit (J2, same discipline as the real
    ``parallel.exchange.overlay_hub[hub]`` entry) must flag the inexact
    avals."""
    import jax.numpy as jnp

    from p2p_gossip_tpu.staticcheck.jaxpr_audit import audit_entry
    from p2p_gossip_tpu.staticcheck.registry import AuditEntry, AuditSpec

    def bad_overlay_hub(recon, hub_local, hub_block):
        # The seeded bug: per-shard row offsets via float32 arithmetic.
        k, h = hub_local.shape
        n_loc = recon.shape[0] // k
        offs = jnp.arange(k, dtype=jnp.float32) * jnp.float32(n_loc)
        flat = (hub_local.astype(jnp.float32) + offs[:, None])
        return recon.at[flat.astype(jnp.int32).reshape(-1)].set(hub_block)

    def spec():
        return AuditSpec(
            args=(
                jnp.zeros((16, 2), dtype=jnp.uint32),
                jnp.zeros((4, 2), dtype=jnp.int32),
                jnp.zeros((8, 2), dtype=jnp.uint32),
            ),
            integer_only=True,
        )

    entry = AuditEntry(
        name="fixtures.hub_bad_overlay",
        fn=bad_overlay_hub, spec=spec,
    )
    violations = audit_entry(entry)
    return {
        "fixture": "hub",
        "ok": not violations,  # must come back False
        "violations": [v.as_dict() for v in violations],
    }


def async_fixture() -> dict:
    """Audit a deliberately-bad async staleness accounting step: the
    per-tick ``staleness`` column (added-lateness word-folds charged
    against the pre-advance landed view) tallied through float32 — the
    dtype leak that saturates the counter low past the 2^24 mantissa
    and silently blesses a broken staleness bound. The integer-only
    audit (J2, the discipline the real async runner entries are
    registered under) must flag the inexact avals."""
    import jax.numpy as jnp

    from p2p_gossip_tpu.staticcheck.jaxpr_audit import audit_entry
    from p2p_gossip_tpu.staticcheck.registry import AuditEntry, AuditSpec

    def bad_staleness_row(landed_view, amounts):
        # The seeded bug: remote late-folds counted in float32. Exact
        # only below 2^24 folds — a 100K-node mesh at full frontier
        # blows past it within a run, rounding the column down.
        remote = (landed_view != 0).any(axis=-1)
        folds = remote.astype(jnp.float32).sum(axis=-1)
        stale = (folds * amounts.astype(jnp.float32)).sum()
        return stale.astype(jnp.uint32)

    def spec():
        return AuditSpec(
            args=(
                jnp.zeros((2, 16, 2), dtype=jnp.uint32),
                jnp.zeros((2,), dtype=jnp.int32),
            ),
            integer_only=True,
        )

    entry = AuditEntry(
        name="fixtures.async_bad_staleness_row",
        fn=bad_staleness_row, spec=spec,
    )
    violations = audit_entry(entry)
    return {
        "fixture": "async",
        "ok": not violations,  # must come back False
        "violations": [v.as_dict() for v in violations],
    }


def meshfact_fixture() -> dict:
    """Seeded axis-split drift: the campaign drivers bake the
    (replicas, nodes) factorization into every jit signature, so
    ``auto_axis_split`` must be stable under the few-percent wobble its
    "rough" node-byte estimate is allowed (``estimate_node_bytes``
    docstring) — an estimate that straddles a shard boundary silently
    recompiles every campaign batch. The fixture lands the estimate ON
    the 2-shard boundary and wobbles it +/-2%: a drift-stable model
    expects ONE distinct split; the sentinel must measure two."""
    from p2p_gossip_tpu.parallel.mesh import auto_axis_split
    from p2p_gossip_tpu.staticcheck.recompile import SentinelReport

    n_devices, hbm = 8, 1_000_000
    # The seeded bug: node_bytes / 2 == hbm exactly, so +2% drift tips
    # the factorization from (4, 2) to (2, 4).
    base = 2 * hbm
    splits = {
        auto_axis_split(n_devices, int(base * drift), hbm_bytes=hbm)
        for drift in (0.98, 1.0, 1.02)
    }
    expected = {"distinct_splits": 1}
    measured = {"distinct_splits": len(splits)}
    report = SentinelReport(
        ok=measured == expected, expected=expected, measured=measured,
        cells=3,
    )
    return {
        "fixture": "meshfact",
        "ok": report.ok,  # must come back False
        "violations": [{"rule": "meshfact-sentinel", "message": m}
                       for m in report.violations()],
        "expected": expected,
        "measured": measured,
    }


def run_fixture(name: str) -> dict:
    if name == "f64":
        return f64_fixture()
    if name == "recompile":
        return recompile_fixture()
    if name == "prng":
        return prng_fixture()
    if name == "telemetry":
        return telemetry_fixture()
    if name == "digest":
        return digest_fixture()
    if name == "exchange":
        return exchange_fixture()
    if name == "meshfact":
        return meshfact_fixture()
    if name == "async":
        return async_fixture()
    if name == "hub":
        return hub_fixture()
    raise ValueError(f"unknown fixture {name!r}; valid: {FIXTURES}")
