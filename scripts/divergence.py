"""Cross-engine divergence bisector — compare digest streams, name the tick.

    python scripts/divergence.py                     # all pairs, tiny config
    python scripts/divergence.py --pair native-sync --n 128 --horizon 32
    python scripts/divergence.py --inject-fault 7    # self-test: must name 7
    python scripts/divergence.py --json              # one JSON line on stdout

Runs the same seeded workload through two engine configurations, collects
their per-tick state digests (telemetry/digest.py), and reports the first
tick where the streams disagree (telemetry/compare.py). Because engines
that agree produce bit-identical digests, a clean run reports zero
divergence across every pair, and any disagreement is located exactly —
no binary search, no second run.

Pairs:

  native-sync      host event engine (runtime/native's reference
                   semantics, digested through the ``on_tick`` hook)
                   vs the compiled ``engine.sync`` tick kernel
  sync-campaign    solo ``engine.sync`` run vs replica 0 of a vmapped
                   flood campaign (``batch.campaign``)
  pushpull-campaign  solo ``models.protocols`` push-pull run vs replica
                   0 of the vmapped protocol campaign
  sync-sharded     solo ``engine.sync`` vs the shard_map flood runner on
                   a 2x2 mesh (skipped when fewer than 4 devices)
  sync-delta       sharded flood runner with the dense state-slice
                   exchange vs the same runner with the sparse
                   frontier-delta exchange (``exchange="delta"``) —
                   delta's OR-monotone merge must be bit-identical, so
                   shard 0's digest streams must agree tick for tick
                   (skipped when fewer than 4 devices)
  sharded-campaign solo node-sharded flood run vs replica 0 of the
                   factorized (replicas x nodes) campaign
                   (``batch.campaign_sharded``) on the same node-shard
                   count (skipped when fewer than 4 devices)
  sync-async       sharded flood runner with cross-shard delays clamped
                   to K=2 host-side (the async contract's reference
                   semantics, ``parallel.async_ticks.clamp_flood_delays``)
                   vs the bounded-staleness async runner
                   (``exchange="async"``, ``async_k=2``) — the K-ahead
                   double-buffered frontier must be bit-identical to
                   the clamped-delay sync run, tick for tick (skipped
                   when fewer than 4 devices)
  sync-hub         sharded flood runner with the dense exchange vs the
                   degree-split hub/tail transport (``exchange="hub"``,
                   ``hub_rows=8`` forced — the tiny ER workload has no
                   natural hub set) — the allreduced hub block plus the
                   sparse tail must OR back to the dense frontier
                   bit-identically (skipped when fewer than 4 devices)

``--inject-fault T`` is the bisector's self-test: after collecting each
pair it flips one bit of the second stream's digest at tick T and
asserts the comparison names exactly T — exit 0 iff every pair locates
the fault, making a blind bisector loudly non-zero. Without injection,
exit 0 iff every pair is divergence-free; a real divergence additionally
dumps a +/- ``--window`` frontier capture around the named tick
(per-node received totals and seen counts from the host engine for
native-sync; both streams' digest windows otherwise).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

PAIRS = (
    "native-sync",
    "sync-campaign",
    "pushpull-campaign",
    "sync-sharded",
    "sync-delta",
    "sharded-campaign",
    "sync-async",
    "sync-hub",
)


def _setup_backend() -> None:
    from p2p_gossip_tpu.utils.platform import (
        cpu_requested,
        force_cpu_backend_if_requested,
    )

    if cpu_requested():
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    force_cpu_backend_if_requested()


def _capture_events(run) -> list:
    """Run ``run()`` with the telemetry sink pointed at a throwaway file
    and hand back the captured event list."""
    from p2p_gossip_tpu import telemetry

    fd, path = tempfile.mkstemp(suffix=".jsonl", prefix="divergence_")
    os.close(fd)
    telemetry.configure(path, rings=True)
    try:
        run()
    finally:
        telemetry.close()
    events = list(telemetry.events())
    telemetry.reset()
    try:
        os.unlink(path)
    except OSError:
        pass
    return events


def _workload(args):
    """The shared seeded workload: an ER graph and a staggered flood
    schedule (three generation waves exercise the delay line)."""
    from p2p_gossip_tpu.models.generation import Schedule
    from p2p_gossip_tpu.models.topology import erdos_renyi

    graph = erdos_renyi(args.n, args.p, seed=args.seed)
    rng = np.random.default_rng(args.seed)
    origins = rng.integers(0, args.n, args.shares).astype(np.int32)
    gen = (np.arange(args.shares, dtype=np.int32) % 3) * 2
    return graph, Schedule(graph.n, origins, gen)


def pair_native_sync(args):
    from p2p_gossip_tpu.engine.sync import run_sync_sim
    from p2p_gossip_tpu.telemetry import compare

    graph, sched = _workload(args)
    cap = compare.capture_event_digests(graph, sched, args.horizon)
    events = _capture_events(
        lambda: run_sync_sim(graph, sched, args.horizon, chunk_size=args.chunk)
    )
    sync = compare.select_stream(
        compare.digest_streams(events), kernel="engine.sync"
    )
    return cap.digests, sync


def pair_sync_campaign(args):
    from p2p_gossip_tpu.batch.campaign import (
        flood_replicas,
        run_coverage_campaign,
    )
    from p2p_gossip_tpu.engine.sync import run_sync_sim
    from p2p_gossip_tpu.telemetry import compare

    graph, _ = _workload(args)
    reps = flood_replicas(
        graph, args.shares, [args.seed, args.seed + 1], args.horizon
    )
    solo_events = _capture_events(
        lambda: run_sync_sim(
            graph, reps.replica_schedule(0, args.horizon), args.horizon,
            chunk_size=args.chunk,
        )
    )
    camp_events = _capture_events(
        lambda: run_coverage_campaign(graph, reps, args.horizon)
    )
    solo = compare.select_stream(
        compare.digest_streams(solo_events), kernel="engine.sync"
    )
    camp = compare.select_stream(
        compare.digest_streams(camp_events), kernel="batch.campaign",
        replica=0,
    )
    return solo, camp


def pair_pushpull_campaign(args):
    from p2p_gossip_tpu.batch.campaign import (
        flood_replicas,
        run_protocol_campaign,
    )
    from p2p_gossip_tpu.models.generation import Schedule
    from p2p_gossip_tpu.models.protocols import run_pushpull_sim
    from p2p_gossip_tpu.telemetry import compare

    graph, _ = _workload(args)
    reps = flood_replicas(
        graph, args.shares, [args.seed, args.seed + 1], args.horizon
    )
    # The campaign's solo reference: flood-style origins from the replica
    # seed, all generated at t=0 (batch/campaign.py's replica contract).
    origins = (
        np.random.default_rng(args.seed)
        .integers(0, graph.n, args.shares)
        .astype(np.int32)
    )
    sched = Schedule(graph.n, origins, np.zeros(args.shares, dtype=np.int32))
    solo_events = _capture_events(
        lambda: run_pushpull_sim(
            graph, sched, args.horizon, seed=args.seed,
            churn=reps.replica_churn(0), record_coverage=True,
        )
    )
    camp_events = _capture_events(
        lambda: run_protocol_campaign(
            graph, reps, args.horizon, protocol="pushpull"
        )
    )
    solo = compare.select_stream(
        compare.digest_streams(solo_events), kernel="models.protocols"
    )
    camp = compare.select_stream(
        compare.digest_streams(camp_events), kernel="run_protocol_campaign",
        replica=0,
    )
    return solo, camp


def pair_sync_sharded(args):
    import jax

    if len(jax.devices()) < 4:
        return None
    from p2p_gossip_tpu.engine.sync import run_sync_sim
    from p2p_gossip_tpu.parallel.engine_sharded import run_sharded_sim
    from p2p_gossip_tpu.parallel.mesh import make_mesh
    from p2p_gossip_tpu.telemetry import compare

    graph, sched = _workload(args)
    mesh = make_mesh(2, 2)
    solo_events = _capture_events(
        lambda: run_sync_sim(graph, sched, args.horizon, chunk_size=args.chunk)
    )
    sharded_events = _capture_events(
        lambda: run_sharded_sim(
            graph, sched, args.horizon, mesh, chunk_size=args.chunk
        )
    )
    solo = compare.select_stream(
        compare.digest_streams(solo_events), kernel="engine.sync"
    )
    # Shard 0 owns the pass's first chunk_size share slots — with the
    # whole schedule in one chunk that is the solo stream's share set.
    sharded = compare.select_stream(
        compare.digest_streams(sharded_events), kernel="engine_sharded",
        shard=0,
    )
    return solo, sharded


def pair_sync_delta(args):
    import jax

    if len(jax.devices()) < 4:
        return None
    from p2p_gossip_tpu.parallel.engine_sharded import run_sharded_sim
    from p2p_gossip_tpu.parallel.mesh import make_mesh
    from p2p_gossip_tpu.telemetry import compare

    graph, sched = _workload(args)
    mesh = make_mesh(2, 2)
    dense_events = _capture_events(
        lambda: run_sharded_sim(
            graph, sched, args.horizon, mesh, chunk_size=args.chunk,
            ring_mode="sharded",
        )
    )
    delta_events = _capture_events(
        lambda: run_sharded_sim(
            graph, sched, args.horizon, mesh, chunk_size=args.chunk,
            exchange="delta",
        )
    )
    dense = compare.select_stream(
        compare.digest_streams(dense_events), kernel="engine_sharded",
        shard=0,
    )
    delta = compare.select_stream(
        compare.digest_streams(delta_events), kernel="engine_sharded",
        shard=0,
    )
    return dense, delta


def pair_sharded_campaign(args):
    import jax

    if len(jax.devices()) < 4:
        return None
    from p2p_gossip_tpu.batch.campaign import flood_replicas
    from p2p_gossip_tpu.batch.campaign_sharded import run_sharded_campaign
    from p2p_gossip_tpu.parallel.engine_sharded import run_sharded_sim
    from p2p_gossip_tpu.parallel.mesh import make_mesh
    from p2p_gossip_tpu.telemetry import compare

    graph, _ = _workload(args)
    reps = flood_replicas(
        graph, args.shares, [args.seed, args.seed + 1], args.horizon
    )
    devices = jax.devices()
    # Factorized (2 replicas x 2 nodes) mesh vs a solo nodes-only mesh
    # with the SAME node-shard count — the campaign's bitwise contract.
    mesh_c = make_mesh(2, devices=devices[:4], replicas=2)
    mesh_s = make_mesh(2, 1, devices=devices[:2])
    solo_events = _capture_events(
        lambda: run_sharded_sim(
            graph, reps.replica_schedule(0, args.horizon), args.horizon,
            mesh_s, chunk_size=args.shares,
        )
    )
    camp_events = _capture_events(
        lambda: run_sharded_campaign(graph, reps, args.horizon, mesh_c)
    )
    solo = compare.select_stream(
        compare.digest_streams(solo_events), kernel="engine_sharded",
        shard=0,
    )
    camp = compare.select_stream(
        compare.digest_streams(camp_events), kernel="run_sharded_campaign",
        replica=0,
    )
    return solo, camp


def pair_sync_async(args):
    import jax

    if len(jax.devices()) < 4:
        return None
    from p2p_gossip_tpu.models.latency import lognormal_delays
    from p2p_gossip_tpu.parallel import async_ticks
    from p2p_gossip_tpu.parallel.engine_sharded import run_sharded_sim
    from p2p_gossip_tpu.parallel.mesh import make_mesh
    from p2p_gossip_tpu.telemetry import compare

    graph, sched = _workload(args)
    mesh = make_mesh(2, 2)
    delays = lognormal_delays(
        graph, mean_ticks=2.0, sigma=0.5, max_ticks=4, seed=args.seed
    )
    k = 2
    # The async contract: async(K) == sync with cross-shard delays
    # clamped to max(d, K) host-side.  Stream a runs the plain sharded
    # runner on the pre-clamped delay line; stream b runs async K=2 on
    # the original delays.  Per-tick digests must be identical, so
    # --inject-fault bisects this pair like any other.
    ref_delays = async_ticks.clamp_flood_delays(
        graph, 2, k, ell_delays=delays
    )
    sync_events = _capture_events(
        lambda: run_sharded_sim(
            graph, sched, args.horizon, mesh, chunk_size=args.chunk,
            ring_mode="sharded", ell_delays=ref_delays,
        )
    )
    async_events = _capture_events(
        lambda: run_sharded_sim(
            graph, sched, args.horizon, mesh, chunk_size=args.chunk,
            exchange="async", async_k=k, ell_delays=delays,
        )
    )
    sync = compare.select_stream(
        compare.digest_streams(sync_events), kernel="engine_sharded",
        shard=0,
    )
    async_ = compare.select_stream(
        compare.digest_streams(async_events), kernel="engine_sharded",
        shard=0,
    )
    return sync, async_


def pair_sync_hub(args):
    import jax

    if len(jax.devices()) < 4:
        return None
    from p2p_gossip_tpu.parallel.engine_sharded import run_sharded_sim
    from p2p_gossip_tpu.parallel.mesh import make_mesh
    from p2p_gossip_tpu.telemetry import compare

    graph, sched = _workload(args)
    mesh = make_mesh(2, 2)
    dense_events = _capture_events(
        lambda: run_sharded_sim(
            graph, sched, args.horizon, mesh, chunk_size=args.chunk,
            ring_mode="sharded",
        )
    )
    # hub_rows=8 forces a non-empty hub set: the tiny ER workload is
    # too flat for the modeled crossover to pick h > 0 on its own, and
    # an empty hub would degenerate to the delta pair.
    hub_events = _capture_events(
        lambda: run_sharded_sim(
            graph, sched, args.horizon, mesh, chunk_size=args.chunk,
            exchange="hub", hub_rows=8,
        )
    )
    dense = compare.select_stream(
        compare.digest_streams(dense_events), kernel="engine_sharded",
        shard=0,
    )
    hub = compare.select_stream(
        compare.digest_streams(hub_events), kernel="engine_sharded",
        shard=0,
    )
    return dense, hub


_PAIR_FNS = {
    "native-sync": pair_native_sync,
    "sync-campaign": pair_sync_campaign,
    "pushpull-campaign": pair_pushpull_campaign,
    "sync-sharded": pair_sync_sharded,
    "sync-delta": pair_sync_delta,
    "sharded-campaign": pair_sharded_campaign,
    "sync-async": pair_sync_async,
    "sync-hub": pair_sync_hub,
}


def _frontier_window(args, tick: int) -> dict:
    """Host frontier capture around a divergent tick (native-sync)."""
    from p2p_gossip_tpu.telemetry import compare

    graph, sched = _workload(args)
    lo = max(tick - args.window, 0)
    hi = min(tick + args.window, args.horizon - 1)
    cap = compare.capture_event_digests(
        graph, sched, args.horizon, window=(lo, hi)
    )
    return {
        str(t): {
            "received_total": int(cap.received[t].sum()),
            "seen_total": int(cap.seen_counts[t].sum()),
            "top_received": [
                [int(i), int(cap.received[t][i])]
                for i in np.argsort(cap.received[t])[-5:][::-1]
            ],
        }
        for t in sorted(cap.received)
    }


def run_pair(name: str, args) -> dict:
    from p2p_gossip_tpu.telemetry import compare

    built = _PAIR_FNS[name](args)
    if built is None:
        return {"pair": name, "skipped": "needs >= 4 devices"}
    a, b = built
    report: dict = {"pair": name}
    if args.inject_fault is not None:
        t = args.inject_fault
        try:
            faulty = compare.inject_fault(b, t, bit=args.fault_bit)
        except ValueError as e:
            return {**report, "fault_located": False, "error": str(e)}
        div = compare.first_divergence(a, faulty)
        report["fault_tick"] = t
        report["located_tick"] = div.tick
        report["fault_located"] = div.tick == t
        report["compared"] = div.compared
        return report
    div = compare.first_divergence(a, b)
    report.update(div.as_dict())
    if div.diverged:
        lo = max(div.tick - args.window, 0)
        hi = div.tick + args.window
        report["digest_window"] = {
            "a": {str(t): a[t] for t in sorted(a) if lo <= t <= hi},
            "b": {str(t): b[t] for t in sorted(b) if lo <= t <= hi},
        }
        if name == "native-sync":
            report["frontier"] = _frontier_window(args, div.tick)
    return report


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--pair", choices=PAIRS, action="append",
                    help="pair(s) to compare (default: all)")
    ap.add_argument("--n", type=int, default=96, help="nodes")
    ap.add_argument("--p", type=float, default=0.08, help="ER edge prob")
    ap.add_argument("--shares", type=int, default=4)
    ap.add_argument("--horizon", type=int, default=24)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--chunk", type=int, default=32,
                    help="solo/sharded share-chunk size")
    ap.add_argument("--inject-fault", type=int, default=None, metavar="T",
                    help="self-test: flip one digest bit at tick T in each "
                    "pair's second stream; exit 0 iff the bisector names T")
    ap.add_argument("--fault-bit", type=int, default=0)
    ap.add_argument("--window", type=int, default=2,
                    help="frontier-capture radius around a divergent tick")
    ap.add_argument("--json", action="store_true",
                    help="one JSON line on stdout")
    ap.add_argument("--with-cost", nargs="?", const="engine.sync",
                    default=None, metavar="SUBSTR",
                    help="after the pairs, also run the compiled-cost "
                    "ledger (scripts/cost_report.py) restricted to "
                    "SUBSTR (default engine.sync) and print its JSON "
                    "line — the battery's flightrec stage")
    args = ap.parse_args()

    _setup_backend()
    pairs = args.pair or list(PAIRS)
    reports = [run_pair(name, args) for name in pairs]

    if args.inject_fault is not None:
        ok = all(
            r.get("fault_located", True) for r in reports
        ) and any("fault_located" in r for r in reports)
    else:
        ok = not any(r.get("diverged") for r in reports)

    out = {"ok": ok, "mode": (
        "inject-fault" if args.inject_fault is not None else "compare"
    ), "pairs": reports}
    if args.json:
        print(json.dumps(out))
    else:
        for r in reports:
            if "skipped" in r:
                print(f"{r['pair']}: SKIPPED ({r['skipped']})")
            elif "error" in r:
                print(f"{r['pair']}: FAULT INJECTION FAILED — {r['error']}")
            elif "fault_located" in r:
                print(
                    f"{r['pair']}: injected fault at tick "
                    f"{r.get('fault_tick')} -> located "
                    f"{r.get('located_tick')} "
                    f"({'OK' if r['fault_located'] else 'MISSED'}, "
                    f"{r.get('compared', 0)} ticks compared)"
                )
            elif r.get("diverged"):
                print(
                    f"{r['pair']}: DIVERGED at tick {r['tick']} "
                    f"(a={r['a_value']:#010x} b={r['b_value']:#010x}, "
                    f"{r['matched_head']} ticks agreed first)"
                )
            else:
                print(
                    f"{r['pair']}: clean — {r['compared']} common ticks, "
                    "zero divergence"
                )
        print(f"divergence: {'OK' if ok else 'FAIL'}")

    if args.with_cost:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from cost_report import run_cost_report

        cost = run_cost_report(only=args.with_cost)
        print(json.dumps(cost))
        ok = ok and cost["ok"]
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
