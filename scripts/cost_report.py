"""Compiled-cost observatory — flops, bytes, compile time per entry point.

    python scripts/cost_report.py                  # human table
    python scripts/cost_report.py --json           # one JSON line on stdout
    python scripts/cost_report.py --only engine.sync
    python scripts/cost_report.py --exchange       # + dense/delta crossover
    P2P_TELEMETRY=run.jsonl python scripts/cost_report.py   # + counter events

Lowers and compiles every staticcheck-registered entry point on the
default device and harvests what XLA already knows but nobody looks at:
``cost_analysis()`` flops and bytes-accessed, ``memory_analysis()``
temp/argument/output footprints, compile wall time, and jaxpr equation
count (via the auditor's ``iter_eqns`` — the same walk the invariant
rules use). The result is the per-kernel cost ledger: a refactor that
doubles an entry's flops or compile time shows up as a diff in this
report before it shows up as a slow campaign.

When the telemetry sink is enabled each figure is also emitted as a
``counter`` event named ``cost.<entry>.<field>``, so a run report
(scripts/run_report.py) carries the cost ledger of the binary that
produced it. bench.py embeds the ``--only engine.sync --json`` output
as its ``cost`` field. Platform is labeled — CPU figures are CPU
figures, not chip numbers.

``--exchange`` adds the frontier-exchange crossover: one sharded flood
run per topology family under ``exchange="delta"``, reporting modeled
dense vs achieved delta words/tick (the runner's on-device counters)
priced by the shared traffic model
`parallel.exchange.modeled_exchange_words_per_tick`, and which path
wins at that scale.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

COST_FIELDS = (
    "flops", "bytes_accessed", "compile_wall_s", "jaxpr_eqns",
    "temp_bytes", "argument_bytes", "output_bytes",
)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _setup_backend() -> None:
    from p2p_gossip_tpu.utils.platform import (
        cpu_requested,
        force_cpu_backend_if_requested,
    )

    if cpu_requested():
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    force_cpu_backend_if_requested()


def _cost_dict(compiled) -> dict:
    """Flops/bytes out of ``cost_analysis()`` — tolerates both the
    list-of-dicts and plain-dict shapes across jax versions, and
    backends that return nothing."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    out = {}
    if "flops" in ca:
        out["flops"] = float(ca["flops"])
    if "bytes accessed" in ca:
        out["bytes_accessed"] = float(ca["bytes accessed"])
    return out


def _memory_dict(compiled) -> dict:
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return {}
    out = {}
    for field, attr in (
        ("temp_bytes", "temp_size_in_bytes"),
        ("argument_bytes", "argument_size_in_bytes"),
        ("output_bytes", "output_size_in_bytes"),
    ):
        v = getattr(mem, attr, None)
        if v is not None:
            out[field] = int(v)
    return out


def cost_entry(entry) -> dict:
    """Lower + compile one registered entry and harvest its cost row.
    Never raises — a failing entry gets an ``error`` field."""
    import jax

    from p2p_gossip_tpu.staticcheck.jaxpr_audit import iter_eqns

    row: dict = {"entry": entry.name}
    try:
        spec = entry.spec()
        fn = spec.fn if spec.fn is not None else entry.fn
        wrapped = lambda *args, _fn=fn, _kw=spec.kwargs: _fn(*args, **_kw)  # noqa: E731
        closed = jax.make_jaxpr(wrapped)(*spec.args)
        row["jaxpr_eqns"] = sum(1 for _ in iter_eqns(closed.jaxpr))
        t0 = time.monotonic()
        compiled = jax.jit(wrapped).lower(*spec.args).compile()
        row["compile_wall_s"] = round(time.monotonic() - t0, 3)
        row.update(_cost_dict(compiled))
        row.update(_memory_dict(compiled))
        row["ok"] = True
    except Exception as e:
        row["ok"] = False
        row["error"] = f"{type(e).__name__}: {e}"[:500]
    return row


def run_cost_report(only: str | None = None) -> dict:
    """The full ledger: one row per registered entry (filtered by the
    ``only`` substring), counter events when the sink is on."""
    import jax

    from p2p_gossip_tpu import telemetry
    from p2p_gossip_tpu.staticcheck import entrypoints, registry

    entrypoints.load_all()
    entries = [
        e for e in registry.all_entries()
        if only is None or only in e.name
    ]
    rows = []
    for entry in entries:
        row = cost_entry(entry)
        rows.append(row)
        if telemetry.enabled() and row.get("ok"):
            for field in COST_FIELDS:
                if field in row:
                    telemetry.emit_counter(
                        f"cost.{entry.name}.{field}", row[field]
                    )
        log(f"cost: {entry.name}: "
            + (f"flops={row.get('flops', 0):.0f} "
               f"bytes={row.get('bytes_accessed', 0):.0f} "
               f"eqns={row.get('jaxpr_eqns', '?')} "
               f"compile={row.get('compile_wall_s', 0):.2f}s"
               if row.get("ok") else f"ERROR {row.get('error')}"))
    ok = all(r.get("ok") for r in rows) and bool(rows)
    return {
        "ok": ok,
        "platform": jax.devices()[0].platform,
        "entries_costed": len(rows),
        "total_compile_wall_s": round(
            sum(r.get("compile_wall_s", 0.0) for r in rows), 2
        ),
        "entries": rows,
    }


#: Topology families the exchange crossover is priced on — one
#: representative small instance each (CPU-cheap; the large-N numbers
#: come from scripts/mesh_rehearsal.py --exchange).
EXCHANGE_FAMILIES = ("erdos_renyi", "barabasi_albert", "watts_strogatz",
                     "ring")


def _exchange_family_graph(family: str, n: int, seed: int):
    from p2p_gossip_tpu.models import topology

    if family == "erdos_renyi":
        return topology.erdos_renyi(n, 0.08, seed=seed)
    if family == "barabasi_albert":
        return topology.barabasi_albert(n, 2, seed=seed)
    if family == "watts_strogatz":
        return topology.watts_strogatz(n, 4, 0.1, seed=seed)
    if family == "ring":
        return topology.ring_graph(n)
    raise ValueError(f"unknown family {family!r}")


def run_exchange_report(
    n: int = 96, horizon: int = 24, seed: int = 0,
    families: tuple[str, ...] | None = None,
) -> dict:
    """Modeled-vs-achieved exchange words per tick, per topology family.

    Runs the sharded flood runner once per family with the sparse
    frontier-delta exchange, once with the degree-split hub/tail
    transport (``exchange="hub"``, an 8-row hub set forced so the split
    is exercised at this tiny scale — real graphs let the modeled
    crossover in ``hub.crossover_h`` choose), and folds the runner's
    achieved-traffic counters (``stats.extra['exchange']``) against the
    shared model (`parallel.exchange.modeled_exchange_words_per_tick` —
    the same formula bench.py and the engines price with). ``winner``
    names the cheapest path per family at this scale; the crossovers
    are visible as ``dense_over_delta`` and ``delta_over_hub``
    (achieved-word ratios — > 1 means the sparser path pays for
    itself)."""
    import jax
    import numpy as np

    from p2p_gossip_tpu import telemetry
    from p2p_gossip_tpu.models.generation import Schedule
    from p2p_gossip_tpu.parallel.engine_sharded import run_sharded_sim
    from p2p_gossip_tpu.parallel.mesh import make_mesh

    n_dev = len(jax.devices())
    if n_dev < 4:
        return {"ok": True, "skipped": f"needs >= 4 devices, have {n_dev}"}
    mesh = make_mesh(4, n_dev // 4)
    rows = []
    for family in families or EXCHANGE_FAMILIES:
        graph = _exchange_family_graph(family, n, seed)
        rng = np.random.default_rng(seed)
        origins = rng.integers(0, graph.n, 8).astype(np.int32)
        gens = (np.arange(8, dtype=np.int32) % 3) * 2
        sched = Schedule(graph.n, origins, gens)
        row: dict = {"family": family, "n": graph.n}
        try:
            stats = run_sharded_sim(
                graph, sched, horizon, mesh, chunk_size=32,
                exchange="delta",
            )
            ex = dict(stats.extra.get("exchange", {}))
            dense = ex.get("modeled_dense_words_per_tick", 0)
            achieved = ex.get("achieved_delta_words_per_tick", 0.0)
            row.update(ex)
            hub_stats = run_sharded_sim(
                graph, sched, horizon, mesh, chunk_size=32,
                exchange="hub", hub_rows=8,
            )
            hub_ex = dict(hub_stats.extra.get("exchange", {}))
            hub_achieved = hub_ex.get("achieved_delta_words_per_tick", 0.0)
            row["hub"] = hub_ex
            costs = {"dense": dense or None, "delta": achieved or None,
                     "hub": hub_achieved or None}
            row["winner"] = min(
                (k for k, v in costs.items() if v),
                key=lambda k: costs[k], default="dense",
            )
            row["dense_over_delta"] = round(
                dense / achieved, 3) if achieved else None
            row["delta_over_hub"] = round(
                achieved / hub_achieved, 3) if hub_achieved else None
            row["ok"] = True
        except Exception as e:  # noqa: BLE001 - ledger must not die
            row["ok"] = False
            row["error"] = f"{type(e).__name__}: {e}"[:500]
        rows.append(row)
        if telemetry.enabled() and row.get("ok"):
            for field in ("modeled_dense_words_per_tick",
                          "modeled_delta_words_per_tick",
                          "achieved_delta_words_per_tick"):
                if row.get(field) is not None:
                    telemetry.emit_counter(
                        f"cost.exchange.{family}.{field}", row[field]
                    )
        log(f"exchange: {family}: "
            + (f"dense={row.get('modeled_dense_words_per_tick')} "
               f"delta~{row.get('achieved_delta_words_per_tick', 0):.1f} "
               f"hub~{(row.get('hub') or {}).get('achieved_delta_words_per_tick', 0):.1f} "
               f"winner={row.get('winner')}"
               if row.get("ok") else f"ERROR {row.get('error')}"))
    return {
        "ok": all(r.get("ok") for r in rows),
        "platform": jax.devices()[0].platform,
        "families": rows,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true",
                    help="one JSON line on stdout instead of the table")
    ap.add_argument("--only", metavar="SUBSTR", default=None,
                    help="restrict to entries whose name contains SUBSTR")
    ap.add_argument("--exchange", action="store_true",
                    help="also price the dense/delta frontier exchange "
                    "per topology family (modeled vs achieved words/tick)")
    ap.add_argument("--exchange-only", action="store_true",
                    help="skip the entry ledger; print just the exchange "
                    "crossover JSON (bench.py's `exchange` field)")
    ap.add_argument("--families", default=None, metavar="A,B",
                    help="comma list of topology families for the "
                    "exchange crossover (default: all)")
    args = ap.parse_args()

    _setup_backend()
    fams = tuple(args.families.split(",")) if args.families else None
    if args.exchange_only:
        ex = run_exchange_report(families=fams)
        print(json.dumps(ex))
        return 0 if ex["ok"] else 1
    report = run_cost_report(only=args.only)
    if args.exchange:
        report["exchange"] = run_exchange_report(families=fams)
        report["ok"] = report["ok"] and report["exchange"]["ok"]

    if args.json:
        print(json.dumps(report))
    else:
        print(f"cost report: {report['entries_costed']} entries on "
              f"{report['platform']} "
              f"(total compile {report['total_compile_wall_s']}s)")
        hdr = (f"{'entry':<48} {'flops':>12} {'bytes':>12} "
               f"{'eqns':>6} {'compile_s':>9}")
        print(hdr)
        print("-" * len(hdr))
        for r in report["entries"]:
            if not r.get("ok"):
                print(f"{r['entry']:<48} ERROR: {r.get('error')}")
                continue
            print(f"{r['entry']:<48} "
                  f"{r.get('flops', 0):>12.0f} "
                  f"{r.get('bytes_accessed', 0):>12.0f} "
                  f"{r.get('jaxpr_eqns', 0):>6d} "
                  f"{r.get('compile_wall_s', 0):>9.3f}")
        ex = report.get("exchange")
        if ex is not None:
            if "skipped" in ex:
                print(f"exchange crossover: SKIPPED ({ex['skipped']})")
            else:
                print("exchange crossover (words/tick, "
                      f"{ex['platform']}):")
                for r in ex["families"]:
                    if not r.get("ok"):
                        print(f"  {r['family']:<16} ERROR: "
                              f"{r.get('error')}")
                        continue
                    print(
                        f"  {r['family']:<16} "
                        f"dense={r.get('modeled_dense_words_per_tick')} "
                        f"delta={r.get('achieved_delta_words_per_tick', 0):.1f} "
                        f"(cap={r.get('capacity')}, "
                        f"occ={r.get('delta_occupancy', 0):.3f}) "
                        f"-> {r.get('winner')}"
                    )
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
