"""One-shot profiled bench + trace parse (VERDICT round-4 item #4).

Every perf claim in the repo inherits the error bar of the modeled
roofline (`DeviceGraph.hbm_bytes_per_tick`, engine/sync.py): achieved
GB/s figures are modeled-bytes / measured-wall. This script calibrates
that model against the chip's own counters, once, on hardware:

1. runs bench.py with its opt-in profiler capture enabled
   (P2P_BENCH_PROFILE_DIR — the timed pass runs under
   jax.profiler.trace and the JSON row is stamped "profiled");
2. parses the captured XPlane trace with the xprof converter
   (roofline_model + overview tools: per-HLO-op self time, measured
   memory bandwidth, HBM bandwidth);
3. emits the bench row (pass-through) plus a `profile_summary` JSON
   line: total device time, measured HBM bytes (sum over ops of
   hbm_bw x self_time), the bench's modeled bytes, and the calibration
   factor measured/modeled;
4. gzips the xplane.pb into docs/artifacts/ so the profile itself is a
   committed artifact, not just a derived number.

Every parse step is defensive: a trace the axon platform writes
differently (tracing through the tunnel was unvalidated before this
stage first ran) still yields the bench row, the committed capture, and
a summary row carrying the parse error — evidence never goes to zero.

Usage:
  python scripts/profile_capture.py            # real chip via bench.py
  python scripts/profile_capture.py --smoke    # CPU smoke (CI contract)
"""

import argparse
import glob
import gzip
import json
import os
import shutil
import subprocess
import sys
import tempfile
from datetime import datetime, timezone

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART_DIR = os.path.join(REPO, "docs", "artifacts")


def log(msg: str) -> None:
    print(f"[profile] {msg}", file=sys.stderr, flush=True)


def gviz_rows(tool_json: str | bytes) -> tuple[list[dict], dict]:
    """Flatten one gviz DataTable JSON into [{col_id: value}] + props.

    The converter emits either a bare table or a list of tables; the
    first table carries the per-op rows for every tool used here.
    """
    obj = json.loads(
        tool_json if isinstance(tool_json, str) else tool_json.decode()
    )
    tbl = obj[0] if isinstance(obj, list) else obj
    cols = [c["id"] for c in tbl.get("cols", [])]
    rows = []
    for r in tbl.get("rows", []):
        cells = [c.get("v") if isinstance(c, dict) else None for c in r["c"]]
        rows.append(dict(zip(cols, cells)))
    return rows, tbl.get("p", {})


def fnum(x) -> float:
    """gviz cells arrive as float, int, or formatted string — or None."""
    if x is None:
        return 0.0
    try:
        return float(str(x).replace(",", ""))
    except ValueError:
        return 0.0


def _is_op_row(r: dict) -> bool:
    # Per-op rows have rank > 0; aggregate rows (step="Total"/program)
    # and the IDLE pseudo-op must not enter the sums. Shared by the
    # fallback decision in summarize_trace and the aggregation filter in
    # summarize_rows so the two can never judge different rows.
    return fnum(r.get("rank")) > 0 and r.get("operation") != "IDLE"


def _infeed_flag(r: dict) -> bool:
    # gviz cells can arrive bool, string ("True"/"False"), or
    # numeric (1/0) — see fnum's docstring; bool("False") is True,
    # so normalize via str or a string/numeric-typed table would
    # silently re-double the sums.
    return str(r.get("include_infeed_outfeed")).lower() in (
        "true", "1", "1.0"
    )


def dedup_per_flag_copies(op_rows: list[dict], summary: dict) -> list[dict]:
    """Drop the roofline table's second per-flag copy.

    The table arrives TWICE — one full copy per include_infeed_outfeed
    setting (verified on the committed 20260801T085701Z capture: 258
    rows = 129 ops x exactly 2, the two copies differing only in that
    flag). Summing both doubles self-time and bytes; keep the
    infeed-excluded copy (device compute only — infeed through the
    tunnel is transfer, not engine work). Factored out of
    summarize_trace so the 2x fix is unit-testable without an xprof
    trace (tests/test_scripts.py).
    """
    flags = {_infeed_flag(r) for r in op_rows}
    if len(flags) <= 1:
        if flags == {True}:
            # Only the infeed-INCLUDED copy is present: nothing to drop,
            # but the sums now follow the opposite convention from the
            # kept-copy (infeed-excluded) norm — stamp it so downstream
            # readers aren't left to infer which convention applies
            # (round-5 advisor finding).
            summary["dedup_note"] = "only infeed-included copy present"
        return op_rows
    kept = [r for r in op_rows if not _infeed_flag(r)]
    # A kept copy at/below half is expected (the infeed-included copy
    # may legitimately carry extra infeed/outfeed-only rows); an empty
    # or larger-than-half kept copy means the table layout changed —
    # keep the sums but say so.
    if not kept or len(kept) * 2 > len(op_rows):
        summary["dedup_note"] = (
            f"per-flag split unexpected: kept {len(kept)} of "
            f"{len(op_rows)} rows"
        )
    return kept


def summarize_rows(rows: list[dict], props: dict, summary: dict) -> dict:
    """Aggregate per-op rows (either tool) into the summary dict —
    split from summarize_trace so synthetic gviz rows can exercise the
    aggregation (incl. the per-flag dedup) in CI, where real TPU
    roofline tables never appear.
    """
    op_rows = dedup_per_flag_copies(
        [r for r in rows if _is_op_row(r)], summary
    )
    total_self_us = sum(fnum(r.get("total_self_time")) for r in op_rows)
    hbm_bytes = sum(
        fnum(r.get("hbm_bw")) * fnum(r.get("total_self_time")) * 1e3
        for r in op_rows
    )
    measured_bytes = sum(
        fnum(r.get("measured_memory_bw")) * fnum(r.get("total_self_time"))
        * 1e3
        for r in op_rows
    )
    summary.update(
        op_rows=len(op_rows),
        ops_with_hbm_bw=sum(1 for r in op_rows if fnum(r.get("hbm_bw")) > 0),
        total_self_time_us=round(total_self_us, 1),
        measured_hbm_bytes=round(hbm_bytes),
        measured_mem_bytes=round(measured_bytes),
        peak_hbm_bw_gbps=fnum(props.get("peak_hbm_bw")),
        device_type=props.get("device_type", ""),
        top_ops=[
            {
                "op": r.get("operation"),
                "category": r.get("category"),
                "self_us": fnum(r.get("total_self_time")),
                "hbm_gbps": fnum(r.get("hbm_bw")),
                "bound_by": r.get("bound_by"),
            }
            for r in sorted(
                op_rows,
                key=lambda r: -fnum(r.get("total_self_time")),
            )[:10]
        ],
    )
    return summary


def summarize_trace(pb_path: str) -> dict:
    """Aggregate measured op time + HBM bytes from one xplane.pb.

    Bytes come from the roofline_model tool's per-op rows:
    hbm_bw [GB/s] x total_self_time [us] = bytes x 1e-3. Ops with no
    HBM figure (CPU traces; infeed) contribute zero — the summary
    records how many ops carried a nonzero figure so a reader can tell
    "measured 0 bytes" from "tool had no counters".
    """
    from xprof.convert import raw_to_tool_data as rtd

    summary: dict = {"trace": os.path.basename(pb_path)}
    rows, props = gviz_rows(
        rtd.xspace_to_tool_data([pb_path], "roofline_model", {})[0]
    )
    # The tool emits aggregate rows (step="Total"/program) alongside
    # per-op rows (rank > 0); only per-op rows sum without double count.
    summary["tool"] = "roofline_model"
    if not any(_is_op_row(r) for r in rows):
        # CPU traces (and possibly the axon plugin's) leave the roofline
        # table empty; hlo_stats carries the same self-time +
        # hbm_bw/measured_memory_bw columns per HLO op.
        rows, _ = gviz_rows(
            rtd.xspace_to_tool_data([pb_path], "hlo_stats", {})[0]
        )
        for r in rows:  # hlo_stats names the op column differently —
            r.setdefault("operation", r.get("hlo_op_name"))  # alias BEFORE
        summary["tool"] = "hlo_stats"  # the IDLE filter in
        # summarize_rows, or IDLE rows slip through it
    return summarize_rows(rows, props, summary)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="CPU smoke shapes (exercises capture+parse only)")
    ap.add_argument("--art-dir", default=ART_DIR,
                    help="where the gzipped capture + summary land")
    ap.add_argument("--keep-trace-mb", type=float, default=64.0,
                    help="skip committing captures gzipping above this")
    args = ap.parse_args()

    stamp = datetime.now(timezone.utc).strftime("%Y%m%dT%H%M%SZ")
    trace_dir = tempfile.mkdtemp(prefix="p2p_profile_")
    env = dict(os.environ)
    env["P2P_BENCH_PROFILE_DIR"] = trace_dir
    if args.smoke:
        env["P2P_BENCH_SMOKE"] = "1"
        # Forced, not setdefault: the operator shell usually exports
        # JAX_PLATFORMS=axon, and a smoke run must never wait on the
        # tunnel.
        env["JAX_PLATFORMS"] = "cpu"

    # bench.py owns the device wait / CPU fallback / JSON contract; this
    # wrapper only adds the capture env and the parse. Pass stderr
    # through so the battery record keeps bench's own diagnostics.
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, cwd=REPO, capture_output=True, text=True,
    )
    sys.stderr.write(proc.stderr)
    bench_rows = []
    for line in proc.stdout.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            bench_rows.append(json.loads(line))
        except json.JSONDecodeError:
            log(f"non-JSON bench stdout: {line[:120]}")
    for row in bench_rows:
        print(json.dumps(row), flush=True)
    if proc.returncode != 0:
        log(f"bench.py rc={proc.returncode}; no trace to parse")
        shutil.rmtree(trace_dir, ignore_errors=True)
        return proc.returncode

    summary: dict = {"kind": "profile_summary", "utc_stamp": stamp}
    # The bench row's metric names the platform it actually ran on —
    # carry it so the summary (and the battery report) self-describe
    # CPU vs TPU, per the repo's labeling discipline.
    bench_metric = bench_rows[0]["metric"] if bench_rows else ""
    summary["bench_metric"] = bench_metric
    cpu_fallback = not args.smoke and "CPU" in bench_metric
    if cpu_fallback:
        # A wedged tunnel turned the profiled pass into bench.py's
        # reduced CPU config. That trace answers nothing about HBM: do
        # NOT commit it as chip evidence, and exit nonzero so the
        # battery records the stage not-ok and --skip-done re-fires it
        # on the next window instead of latching a CPU number as the
        # calibration (round-5 review finding).
        summary["error"] = (
            "bench fell back to CPU (tunnel down); no on-chip trace — "
            "stage must re-fire"
        )
        log(summary["error"])
        print(json.dumps(summary), flush=True)
        shutil.rmtree(trace_dir, ignore_errors=True)
        return 1
    pbs = sorted(glob.glob(
        os.path.join(trace_dir, "plugins", "profile", "*", "*.xplane.pb")
    ))
    if not pbs:
        summary["error"] = "no xplane.pb produced under the profile dir"
        print(json.dumps(summary), flush=True)
        shutil.rmtree(trace_dir, ignore_errors=True)
        return 1

    pb = pbs[-1]
    raw_mb = os.path.getsize(pb) / 1e6
    os.makedirs(args.art_dir, exist_ok=True)
    if raw_mb <= args.keep_trace_mb:
        gz = os.path.join(args.art_dir, f"profile_{stamp}.xplane.pb.gz")
        with open(pb, "rb") as fin, gzip.open(gz, "wb") as fout:
            shutil.copyfileobj(fin, fout)
        summary["capture"] = os.path.relpath(gz, REPO)
        summary["capture_raw_mb"] = round(raw_mb, 1)
        log(f"capture committed: {gz} ({raw_mb:.1f} MB raw)")
    else:
        summary["capture"] = None
        summary["capture_raw_mb"] = round(raw_mb, 1)
        log(f"capture too large to commit ({raw_mb:.1f} MB); parsed only")

    try:
        summary.update(summarize_trace(pb))
    except Exception as e:  # parse failure must not lose the capture
        summary["error"] = f"{type(e).__name__}: {e}"
        # Summary-row contract: the aggregate fields are PRESENT with
        # explicit zeros when the parser cannot run at all (e.g. an
        # image without xprof), so consumers read "no measured data"
        # from ops_with_hbm_bw/error instead of hitting missing keys —
        # the same shape a CPU trace with no device-plane rows produces.
        summary.setdefault("op_rows", 0)
        summary.setdefault("ops_with_hbm_bw", 0)
        summary.setdefault("total_self_time_us", 0)
        summary.setdefault("measured_hbm_bytes", 0)
        summary.setdefault("measured_mem_bytes", 0)

    # Calibration, two ways: a bandwidth ratio (measured bytes over the
    # trace's busy time vs the bench's modeled-bytes-over-wall), and —
    # when the bench row carries modeled_bytes_total — a clock-free
    # bytes-to-bytes ratio, which is the cleaner figure.
    for row in bench_rows:
        if "achieved_gbps" in row and row.get("profiled"):
            if summary.get("total_self_time_us", 0) > 0:
                meas_gbps = (
                    summary.get("measured_hbm_bytes", 0)
                    / (summary["total_self_time_us"] * 1e-6) / 1e9
                )
                summary["measured_hbm_gbps_over_self_time"] = round(
                    meas_gbps, 1
                )
                summary["modeled_achieved_gbps"] = row["achieved_gbps"]
                if row["achieved_gbps"]:
                    summary["measured_over_modeled"] = round(
                        meas_gbps / row["achieved_gbps"], 3
                    )
                # Bytes-to-bytes, clock-free: the bench row carries the
                # model's total bytes for the timed pass
                # (ticks x hbm_bytes_per_tick), a fixed per-run figure.
                # Comparing it to the trace's measured byte sum isolates
                # model byte-undercounting from device idle time, which
                # the bandwidth ratio above conflates with it (the
                # profiled bench was busy 1.27 s of its 1.53 s wall).
                modeled_bytes = row.get("modeled_bytes_total", 0)
                if modeled_bytes:
                    summary["modeled_bytes_total"] = modeled_bytes
                    summary["measured_over_modeled_bytes"] = round(
                        summary.get("measured_hbm_bytes", 0) / modeled_bytes,
                        3,
                    )
            break

    print(json.dumps(summary), flush=True)
    summary_path = os.path.join(args.art_dir, f"profile_{stamp}_summary.json")
    with open(summary_path, "w") as f:
        json.dump(summary, f, indent=1)
    log(f"summary written: {summary_path}")
    shutil.rmtree(trace_dir, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
