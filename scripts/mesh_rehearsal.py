"""Multi-chip 1M-mechanics rehearsal on a virtual CPU mesh (VERDICT r2 #6).

Multi-chip TPU hardware is not attached in this environment, so perf
cannot be measured — but the full BASELINE config-5 *mechanics* can be
proven end-to-end at real scale on an 8-virtual-device CPU mesh: build a
>=100K-node graph, run the sharded flood-coverage engine with lognormal
per-edge delay lines under BOTH history-ring layouts, and check

  - bitwise counter + coverage parity against the single-device engine,
  - per-chip ring bytes scale 1/shards in sharded mode,

so the only untested step to a physical v5e-8 is the hardware itself.

Emits one JSON row per (N, ring_mode) on stdout; diagnostics on stderr.
Usage: python scripts/mesh_rehearsal.py [--nodes 100000] [--prob 0.001]
       [--shares 64] [--devices 8] [--skip-parity]
       [--replicas R]  # campaign rehearsal: R seed replicas of the
       node-sharded graph as ONE compiled program on a factorized
       (replica_shards x node_shards) mesh — per-replica bitwise compare
       vs solo sharded runs, warm/fresh timings vs the sequential
       solo-sharded loop (batch/campaign_sharded.py)
       [--out FILE]    # also append every JSON row to FILE (artifact)
       [--protocol flood|pushpull|pull|pushk]   # partnered legs rehearse
       BASELINE config 5's anti-entropy on the same mesh/ring machinery
       [--exchange dense|delta|hub|ab]  # sharded-ring wire format; "ab"
       runs all three and reports achieved exchange words/tick side by
       side ([--hub-rows H] forces the hub-set size on flat graphs)
       [--partition]  # relabel nodes by the cached BFS-grown partition
       so each shard owns one partition (minimal cross-shard edge cut)
       [--async-k "1,2,4"]  # bounded-staleness async legs (flood only):
       one extra sharded leg per K. K=1 is the synchronous program
       routed through the double-buffer and joins the bitwise cross-leg
       checks; K>=2 trades tick-exactness for overlap by contract, so
       those legs assert fixed-point equality instead (equal counters +
       final coverage row) and report wall_s / wall_per_tick_s next to
       the sync legs — the headline sync-vs-async measurement
"""

import argparse
import json
import os
import sys
import time

# Self-locate (PYTHONPATH must stay off the repo — scale_1m.py header).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _campaign_rehearsal(
    args, graph, delays, devices, emit, aux_base=None
) -> int:
    """--replicas leg: one factorized-mesh campaign vs the sequential
    solo-sharded loop it replaces. Certifies, per replica and per
    exchange wire format, that the campaign counters are BITWISE the
    solo node-sharded run's (same node-shard count, same share pad), and
    times warm/fresh walls for both drivers — the throughput claim the
    factorization makes (per-replica warm wall under the sequential
    loop's) lands in the emitted row as ``speedup_warm_per_replica``."""
    import jax
    import numpy as np

    from p2p_gossip_tpu.batch.campaign import flood_replicas
    from p2p_gossip_tpu.batch.campaign_sharded import (
        run_sharded_campaign,
        run_sharded_protocol_campaign,
    )
    from p2p_gossip_tpu.ops.bitmask import num_words
    from p2p_gossip_tpu.parallel.engine_sharded import run_sharded_sim
    from p2p_gossip_tpu.parallel.mesh import make_mesh

    r_shards = args.replica_shards
    if args.devices % r_shards:
        raise SystemExit(
            f"--replica-shards {r_shards} must divide --devices "
            f"{args.devices}"
        )
    n_node_shards = args.devices // r_shards
    # Campaign mesh: replicas x nodes over ALL the devices. Solo
    # baseline mesh: nodes-only with the SAME node-shard count — the
    # mesh a sequential seed loop would actually run on, and the mesh
    # the bitwise contract is stated against (campaign_sharded
    # docstring: same node-shard count, same share pad).
    mesh_c = make_mesh(
        n_node_shards, devices=devices[: args.devices], replicas=r_shards
    )
    mesh_s = make_mesh(n_node_shards, 1, devices=devices[:n_node_shards])
    reps = flood_replicas(
        graph, args.shares,
        list(range(args.seed, args.seed + args.replicas)), args.horizon,
    )
    n_delay_values = len(np.unique(delays[graph.ell()[1]]))

    if args.protocol != "flood":
        from p2p_gossip_tpu.parallel.protocols_sharded import (
            run_sharded_partnered_sim,
        )

        sched_kw = {"protocol": args.protocol, "fanout": args.fanout}

    exchanges = (
        ("dense", "delta", "hub") if args.exchange == "ab"
        else (args.exchange,)
    )
    # Campaign and solo meshes shard nodes the same way, so one cached
    # cut plan (keyed by the node-shard count) serves both drivers.
    aux_cache = (
        (aux_base[0], aux_base[1],
         f"floodcut{n_node_shards}_{aux_base[2]}")
        if aux_base else None
    )
    hub_rows = args.hub_rows or None
    for exchange in exchanges:
        if args.protocol == "flood":
            def run_campaign():
                return run_sharded_campaign(
                    graph, reps, args.horizon, mesh_c, ell_delays=delays,
                    block=args.block, exchange=exchange,
                    hub_rows=hub_rows, aux_cache=aux_cache,
                )

            def run_solo(r):
                return run_sharded_sim(
                    graph, reps.replica_schedule(r, args.horizon),
                    args.horizon, mesh_s, ell_delays=delays,
                    block=args.block, exchange=exchange,
                    hub_rows=hub_rows, aux_cache=aux_cache,
                    chunk_size=reps.shares_per_replica,
                )
        else:
            def run_campaign():
                return run_sharded_protocol_campaign(
                    graph, reps, args.horizon, mesh_c, ell_delays=delays,
                    exchange=exchange, hub_rows=hub_rows, **sched_kw,
                )

            def run_solo(r):
                return run_sharded_partnered_sim(
                    graph, reps.replica_schedule(r, args.horizon),
                    args.horizon, mesh_s, ell_delays=delays,
                    seed=int(reps.seeds[r]) & 0xFFFFFFFF,
                    exchange=exchange, hub_rows=hub_rows,
                    chunk_size=reps.shares_per_replica, **sched_kw,
                )

        # Fresh = compile-inclusive (the one-program claim: ONE compile
        # covers every replica); warm = steady-state batch wall.
        t0 = time.perf_counter()
        result = run_campaign()
        fresh_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        result = run_campaign()
        warm_s = time.perf_counter() - t0
        log(f"campaign[{exchange}]: fresh {fresh_s:.1f}s, "
            f"warm {warm_s:.1f}s ({warm_s / args.replicas:.2f}s/replica)")

        # Sequential baseline: compile once on replica 0 (flood_replicas
        # gives every replica identical shapes, so one executable serves
        # the whole loop — the fairest version of the loop the campaign
        # replaces), then time the warm R-replica loop with the bitwise
        # check folded in.
        t0 = time.perf_counter()
        run_solo(0)
        solo_fresh_s = time.perf_counter() - t0
        equal = []
        t0 = time.perf_counter()
        for r in range(args.replicas):
            st = run_solo(r)
            ok = bool(
                np.array_equal(st.received[: graph.n], result.received[r])
                and np.array_equal(st.sent[: graph.n], result.sent[r])
            )
            equal.append(ok)
            log(f"  replica {r}: solo-vs-campaign bitwise "
                f"{'OK' if ok else 'MISMATCH'} (received + sent)")
        solo_loop_s = time.perf_counter() - t0
        assert all(equal), f"campaign diverges from solo loop: {equal}"

        ring = result.extra["ring"]
        row = {
            "rehearsal": (
                "campaign_sharded" if args.protocol == "flood"
                else f"campaign_sharded_{args.protocol}"
            ),
            "platform": jax.devices()[0].platform,
            "nodes": graph.n,
            "topology": args.topology,
            "edges": graph.num_edges,
            "devices": args.devices,
            "replicas": args.replicas,
            "replica_shards": r_shards,
            "node_shards": n_node_shards,
            "local_replicas": result.extra["mesh"]["local_replicas"],
            "shares_per_replica": args.shares,
            "horizon": args.horizon,
            "delay_values": int(n_delay_values),
            "exchange_mode": exchange,
            "ring_mode": ring["mode"],
            "ring_bytes_per_chip": ring["bytes_per_chip"],
            "pad_shares": num_words(args.shares) * 32,
            "bitwise_equal_replicas": int(sum(equal)),
            "campaign_fresh_s": round(fresh_s, 2),
            "campaign_warm_s": round(warm_s, 2),
            "campaign_warm_per_replica_s": round(warm_s / args.replicas, 3),
            "solo_fresh_s": round(solo_fresh_s, 2),
            "solo_loop_warm_s": round(solo_loop_s, 2),
            "solo_warm_per_replica_s": round(solo_loop_s / args.replicas, 3),
            "speedup_warm_per_replica": round(solo_loop_s / warm_s, 2),
        }
        ex = result.extra.get("exchange")
        if ex is not None:
            row["exchange"] = ex
        emit(row)
        log(f"campaign[{exchange}]: {sum(equal)}/{args.replicas} replicas "
            f"bitwise-equal, warm speedup x{row['speedup_warm_per_replica']}"
            f" per replica vs sequential solo loop")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=100_000)
    ap.add_argument("--prob", type=float, default=0.001)
    ap.add_argument(
        "--topology", choices=("er", "ba"), default="er",
        help="er = config 3/5's Erdos-Renyi; ba = config 4's 1M "
        "scale-free, node-sharded over the mesh",
    )
    ap.add_argument("--baM", type=int, default=3)
    ap.add_argument("--shares", type=int, default=64)
    ap.add_argument("--horizon", type=int, default=48)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--block", type=int, default=8)
    ap.add_argument(
        "--delay-max-ticks", type=int, default=4,
        help="lognormal delay cap (distinct delay values L <= cap)",
    )
    ap.add_argument(
        "--protocol", choices=("flood", "pushpull", "pull", "pushk"),
        default="flood",
        help="which engine leg to rehearse: flood (config 3/5's delivery "
        "mechanics) or a partnered protocol (pushpull = BASELINE config "
        "5's anti-entropy leg) — partnered runs check bitwise equality "
        "BETWEEN the two ring layouts (always) and vs the single-device "
        "engine (unless --skip-parity)",
    )
    ap.add_argument("--fanout", type=int, default=3,
                    help="k for --protocol pushk")
    ap.add_argument(
        "--chunkSize", type=int, default=0,
        help="explicit share-pad width (0 = engine default 4096-share "
        "lane pad). On the VIRTUAL mesh all shards live in one host "
        "process, so the default W=128 pad multiplies every ring/frontier "
        "buffer x8 in one RSS — 1M scale-free (dmax 4517, ~40 GB "
        "full-width ELL) OOMs with it and needs e.g. --chunkSize 64",
    )
    ap.add_argument(
        "--exchange", choices=("dense", "delta", "hub", "ab"),
        default="dense",
        help="frontier-exchange wire format for the sharded-ring leg: "
        "dense state-slice all_gathers (default), sparse frontier-delta "
        "buffers (delta), the degree-split hub/tail transport (hub), or "
        "ab = run ALL sharded legs (dense, delta, hub) and report the "
        "achieved exchange words/tick side by side (the wire-format "
        "crossover measurement at rehearsal scale)",
    )
    ap.add_argument(
        "--hub-rows", type=int, default=0,
        help="force the hub-set size for exchange=hub legs (0 = let the "
        "modeled word-count crossover choose; a forced value is for "
        "small graphs whose flat degree profile yields no natural hubs)",
    )
    ap.add_argument(
        "--async-k", type=str, default="",
        help="comma list of bounded-staleness depths (e.g. '1,2,4'): one "
        "extra sharded async leg per K on the --exchange transport(s). "
        "Flood only (the partnered rehearsal's counters are not "
        "delay-invariant at a fixed horizon); K=1 joins the bitwise "
        "cross-leg checks, K>=2 legs assert equal final counters + final "
        "coverage row and report wall_s per leg",
    )
    ap.add_argument(
        "--partition", action="store_true",
        help="relabel node ids by the BFS-grown partition "
        "(models/topology.partition_labels, one partition per mesh "
        "shard) before running — minimizes the cross-shard edge cut the "
        "delta exchange must ship; labels persist in the --cache npz "
        "under the graph's build fingerprint",
    )
    ap.add_argument(
        "--replicas", type=int, default=0,
        help="R > 0 switches to the CAMPAIGN rehearsal: R seed replicas "
        "of the node-sharded graph as ONE compiled program on a "
        "factorized (replica_shards x node_shards) mesh "
        "(batch/campaign_sharded.py) — each replica checked bitwise vs "
        "its solo sharded run, with warm/fresh timings vs the "
        "sequential solo-sharded loop; works with --protocol and "
        "--exchange (ab runs dense, delta, and hub legs)",
    )
    ap.add_argument(
        "--replica-shards", type=int, default=2,
        help="replica-axis device count for --replicas (node shards "
        "take the rest: 8 devices, 2 replica shards -> a (2, 4) mesh); "
        "must divide --devices",
    )
    ap.add_argument(
        "--out", type=str, default="",
        help="also append every emitted JSON row to this file (the "
        "docs/artifacts/ path for committed evidence)",
    )
    ap.add_argument(
        "--skip-parity", action="store_true",
        help="skip the single-device parity run (halves the wall time); "
        "flood runs still check counter conservation, and every run "
        "(flood or partnered) checks the two ring layouts against each "
        "other bitwise",
    )
    ap.add_argument(
        "--cache", type=str, default="",
        help="npz graph cache, interoperable with scale_1m.py --cache "
        "(same fingerprint scheme) — at N=1M the ER build is ~3.5 min, "
        "so the rehearsal reuses the north-star script's graph",
    )
    args = ap.parse_args()

    async_ks = [int(v) for v in args.async_k.split(",") if v.strip()]
    if any(k < 1 for k in async_ks):
        raise SystemExit("--async-k values must be >= 1")
    if async_ks and (args.protocol != "flood" or args.replicas):
        raise SystemExit(
            "--async-k rehearses the flood legs only (partnered/campaign "
            "counters are not delay-invariant at a fixed horizon)"
        )

    # Virtual mesh: this is a mechanics rehearsal, so CPU is the point —
    # pin it before jax loads and fan the host out to N devices.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={args.devices}"
        ).strip()
    from p2p_gossip_tpu.utils.platform import force_cpu_backend_if_requested

    force_cpu_backend_if_requested()

    import jax
    import numpy as np

    import p2p_gossip_tpu as pg
    from p2p_gossip_tpu.engine.sync import run_flood_coverage
    from p2p_gossip_tpu.models.latency import lognormal_delays
    from p2p_gossip_tpu.parallel.engine_sharded import (
        run_sharded_flood_coverage,
    )
    from p2p_gossip_tpu.parallel.mesh import make_mesh
    from p2p_gossip_tpu.runtime import native

    devices = jax.devices("cpu")
    assert len(devices) >= args.devices, devices
    mesh = make_mesh(args.devices, 1, devices=devices[: args.devices])

    def emit(row: dict) -> None:
        line = json.dumps(row)
        print(line, flush=True)
        if args.out:
            with open(args.out, "a") as f:
                f.write(line + "\n")

    # Cache protocol shared with scale_1m.py (same fingerprint, same
    # load/validate/build/save semantics), so /tmp/er1m.npz built by
    # either script serves both.
    from p2p_gossip_tpu.models.topology import load_or_build_graph_cache

    def build():
        if args.topology == "ba":
            graph = native.native_barabasi_albert(
                args.nodes, m=args.baM, seed=args.seed
            )
            if graph is None:
                graph = pg.barabasi_albert(args.nodes, m=args.baM,
                                           seed=args.seed)
            return graph
        graph = native.native_erdos_renyi(
            args.nodes, args.prob, seed=args.seed
        )
        if graph is None:
            graph = pg.erdos_renyi(args.nodes, args.prob, seed=args.seed)
        return graph

    t0 = time.perf_counter()
    graph = load_or_build_graph_cache(
        args.cache, topology=args.topology, nodes=args.nodes,
        prob=args.prob, ba_m=args.baM, seed=args.seed, build=build, log=log,
    )
    log(
        f"graph: N={graph.n} edges={graph.num_edges} dmax={graph.max_degree}"
        f" ({time.perf_counter() - t0:.1f}s)"
    )

    edge_cut_pct = None
    aux_base = None
    if args.partition:
        # Partition-centric layout: relabel so each mesh shard owns one
        # BFS-grown partition. Labels are a pure function of the graph,
        # so they persist in the same npz under the build fingerprint
        # and the 1M partitioning pass runs once per graph build.
        from p2p_gossip_tpu.models.topology import (
            edge_cut,
            load_or_compute_graph_aux,
            partition_labels,
            partition_order,
            relabel_graph,
            scale_graph_fingerprint,
        )

        fp = scale_graph_fingerprint(
            args.topology, args.nodes, args.prob, args.baM, args.seed
        )
        t0 = time.perf_counter()
        g_for_labels = graph
        labels = load_or_compute_graph_aux(
            args.cache, f"partition{args.devices}_s{args.seed}", fp,
            lambda: partition_labels(
                g_for_labels, args.devices, seed=args.seed
            ),
            log,
        )
        cut = edge_cut(graph, labels)
        edge_cut_pct = round(100 * cut / max(graph.num_edges, 1), 2)
        graph, _ = relabel_graph(graph, partition_order(labels))
        log(
            f"partition: {args.devices} parts, edge cut {cut}"
            f"/{graph.num_edges} ({edge_cut_pct}%) "
            f"({time.perf_counter() - t0:.1f}s)"
        )
        if args.cache:
            # Persist the delta/hub exchange's per-destination cut plan
            # (exchange.cached_flood_plan) in the same npz under the
            # same build fingerprint as the labels. The key must pin
            # everything beyond the build that shapes the cut: the
            # relabel (parts + seed) here, the node-shard count at the
            # use site (solo and campaign meshes shard differently).
            aux_base = (args.cache, fp, f"part{args.devices}_s{args.seed}")

    delays = lognormal_delays(
        graph, mean_ticks=2.0, sigma=0.6, max_ticks=args.delay_max_ticks,
        seed=args.seed,
    )

    if args.replicas:
        return _campaign_rehearsal(
            args, graph, delays, devices, emit, aux_base
        )

    # Host-fit arithmetic (shared by the auto-shrink preflight below and
    # the emitted rows): the virtual mesh concentrates every shard in ONE
    # process, so pad width drives host RSS. avail is read once at
    # startup; because it moves with unrelated processes, the chosen pad
    # — and therefore the ring-bytes rows — can differ between runs of
    # the same command, which is why each row now records pad_shares +
    # host_avail_gb so artifacts are self-describing (round-4 advisor).
    from p2p_gossip_tpu.ops.bitmask import num_words

    avail = float(os.environ.get("P2P_HOST_BUDGET_GB", "0")) * 1e9
    if not avail:
        avail = 0.7 * os.sysconf("SC_AVPHYS_PAGES") * os.sysconf(
            "SC_PAGE_SIZE"
        )
    fw_ell = graph.n * graph.max_degree * 9
    ring_slots_model = args.delay_max_ticks + 1

    def host_total(pad):
        row = num_words(max(args.shares, pad)) * 4
        rings = args.devices * ring_slots_model * graph.n * row
        return fw_ell + rings + 6 * graph.n * row

    if not args.chunkSize:
        # Host-fit preflight: the virtual mesh concentrates every shard in
        # ONE process, so the default 4096-share pad — deliberately
        # faithful to config 5's real per-chip ring footprint — can
        # exceed host RAM where 8 real chips would each hold 1/8th. The
        # dominant terms: the sharded engine's FULL-WIDTH ELL staging
        # (N x dmax x (4B idx + 4B delay + 1B mask), hub-sensitive: 1M BA
        # at dmax 4517 is ~40 GB and OOM-killed the first attempt,
        # docs/artifacts/mesh_ba_1m.log) plus one history ring per
        # virtual device. Auto-shrink the pad only when the model
        # exceeds available RAM, and say so loudly — a shrunk pad keeps
        # every parity/coverage check but stops modeling the real
        # config-5 ring bytes.
        pad = 4096
        while pad > 32 and host_total(pad) > avail:
            pad //= 2
        if pad < 4096:
            args.chunkSize = pad
            log(
                f"host-fit: default 4096-share pad models "
                f"{host_total(4096) / 1e9:.1f} GB on this host "
                f"(> {avail / 1e9:.1f} GB available); shrinking pad to "
                f"{pad} shares ({host_total(pad) / 1e9:.1f} GB). Parity "
                "checks are unaffected; ring-bytes rows no longer model "
                "the real config-5 footprint."
            )
    # The pad the engine actually stages: a chunkSize below the share
    # count cannot narrow the rows past the shares themselves (the
    # engine pads to whole 32-bit words of max(shares, chunk)) — record
    # that width, not the raw flag, or the row misdescribes its own
    # ring_bytes accounting.
    eff_pad = num_words(max(args.shares, args.chunkSize or 4096)) * 32
    if host_total(eff_pad) > avail:
        # Not a silent floor: the preflight cannot shrink below 32, and
        # an explicit --chunkSize is taken as given — either way the run
        # proceeds, but the operator (and the artifact row, via
        # host_fit_ok below) must see the model was not satisfied.
        log(
            f"WARNING host-fit NOT satisfied: pad {eff_pad} still models "
            f"{host_total(eff_pad) / 1e9:.1f} GB > {avail / 1e9:.1f} GB "
            "available; proceeding (OOM risk is the operator's)."
        )
    n_delay_values = len(np.unique(delays[graph.ell()[1]]))
    rng = np.random.default_rng(args.seed)
    origins = rng.integers(0, graph.n, args.shares).astype(np.int32)

    # One driver per leg, same (stats, coverage) contract, so the ring
    # loop below treats flood and partnered protocols uniformly.
    if args.protocol == "flood":
        def run_single():
            return run_flood_coverage(
                graph, origins, args.horizon, ell_delays=delays,
                block=args.block,
                chunk_size=args.chunkSize or None,
            )

        aux_cache = (
            (aux_base[0], aux_base[1],
             f"floodcut{args.devices}_{aux_base[2]}")
            if aux_base else None
        )

        def run_mesh(ring_mode, exchange="dense", async_k=0):
            return run_sharded_flood_coverage(
                graph, origins, args.horizon, mesh, ell_delays=delays,
                block=args.block, ring_mode=ring_mode, exchange=exchange,
                hub_rows=args.hub_rows or None, aux_cache=aux_cache,
                **({"async_k": async_k} if async_k else {}),
                **({"chunk_size": args.chunkSize} if args.chunkSize else {}),
            )
    else:
        from p2p_gossip_tpu.models.protocols import (
            run_pushk_sim, run_pushpull_sim,
        )
        from p2p_gossip_tpu.parallel.protocols_sharded import (
            run_sharded_partnered_sim,
        )

        sched = pg.Schedule(
            graph.n, origins, np.zeros(args.shares, dtype=np.int32)
        )

        chunk_kw = (
            {"chunk_size": args.chunkSize} if args.chunkSize else {}
        )

        def run_single():
            if args.protocol == "pushk":
                return run_pushk_sim(
                    graph, sched, args.horizon, fanout=args.fanout,
                    ell_delays=delays, seed=args.seed, record_coverage=True,
                    **chunk_kw,
                )
            return run_pushpull_sim(
                graph, sched, args.horizon, ell_delays=delays,
                seed=args.seed, record_coverage=True, mode=args.protocol,
                **chunk_kw,
            )

        def run_mesh(ring_mode, exchange="dense", async_k=0):
            return run_sharded_partnered_sim(
                graph, sched, args.horizon, mesh, protocol=args.protocol,
                fanout=args.fanout, ell_delays=delays, seed=args.seed,
                record_coverage=True, ring_mode=ring_mode,
                exchange=exchange, hub_rows=args.hub_rows or None,
                **chunk_kw,
            )

    cov_single = None
    if not args.skip_parity:
        t0 = time.perf_counter()
        stats_1, cov_single = run_single()
        log(f"single-device run: {time.perf_counter() - t0:.1f}s")

    # Leg plan: the replicated-ring leg always runs (layout baseline);
    # the sharded-ring leg runs dense, delta, or both ("ab" — the
    # rehearsal-scale dense/delta crossover measurement). Every pair of
    # legs is checked bitwise-equal below, so a delta leg is certified
    # against whichever dense legs ran.
    legs = [("replicated", "dense", 0)]
    if args.exchange in ("dense", "ab"):
        legs.append(("sharded", "dense", 0))
    if args.exchange in ("delta", "ab"):
        legs.append(("sharded", "delta", 0))
    if args.exchange in ("hub", "ab"):
        legs.append(("sharded", "hub", 0))
    # Async legs ride the same transport(s) as the sync legs so the
    # sync-vs-async wall comparison is transport-for-transport.
    for k in async_ks:
        if args.exchange in ("dense", "ab"):
            legs.append(("sharded", "async-dense", k))
        if args.exchange in ("delta", "ab"):
            legs.append(("sharded", "async-delta", k))
        if args.exchange in ("hub", "ab"):
            legs.append(("sharded", "async-hub", k))

    mesh_runs = []
    for ring_mode, exchange, async_k in legs:
        t0 = time.perf_counter()
        stats_m, cov_m = run_mesh(ring_mode, exchange, async_k)
        wall = time.perf_counter() - t0
        ring = stats_m.extra["ring"]
        if args.protocol == "flood":
            # Conservation holds whether or not the parity leg ran — at
            # N=1M the single-device comparison is prohibitive on the
            # host, but received==forwarded / sent==(gen+fwd)*degree
            # still certify the sharded counters. (Partnered protocols
            # have different counter laws; their always-on check is the
            # cross-ring-mode bitwise equality below.)
            stats_m.check_conservation()
        leg_name = f"{ring_mode}/{exchange}" + (
            f"/K{async_k}" if async_k else ""
        )
        mesh_runs.append((leg_name, stats_m, cov_m, async_k))
        parity = None
        if cov_single is not None:
            if async_k >= 2:
                # K >= 2 shifts per-tick timing by contract (bounded
                # staleness); the fixed point is what must survive.
                parity = bool(
                    stats_m.equal_counts(stats_1)
                    and np.array_equal(
                        np.asarray(cov_single)[-1], np.asarray(cov_m)[-1]
                    )
                )
                assert parity, (
                    f"async leg diverges from the sync fixed point "
                    f"({leg_name})"
                )
            else:
                parity = bool(
                    np.array_equal(cov_single, cov_m)
                    and stats_m.equal_counts(stats_1)
                )
                assert parity, (
                    f"mesh diverges from single-device ({leg_name})"
                )
        row = {
            # Historical label continuity: committed artifacts (e.g.
            # docs/artifacts/mesh_1m.json) carry "sharded_flood_coverage".
            "rehearsal": (
                "sharded_flood_coverage" if args.protocol == "flood"
                else f"sharded_{args.protocol}"
            ),
            "nodes": graph.n,
            "topology": args.topology,
            "edges": graph.num_edges,
            "devices": args.devices,
            "shares": args.shares,
            "delay_values": int(n_delay_values),
            "ring_mode": ring["mode"],
            "ring_slots": ring["slots"],
            "ring_bytes_per_chip": ring["bytes_per_chip"],
            # Self-description (round-4 advisor): the pad the run really
            # used, what the host had, and whether the fit model held —
            # so two runs of the same command that chose different pads
            # are distinguishable from their rows alone.
            "pad_shares": eff_pad,
            "host_avail_gb": round(avail / 1e9, 1),
            "host_fit_ok": bool(host_total(eff_pad) <= avail),
            "coverage_final_min": int(np.asarray(cov_m)[-1].min()),
            "parity_vs_single_device": parity,
            "wall_s": round(wall, 1),
            "wall_per_tick_s": round(wall / max(args.horizon, 1), 4),
            "exchange_mode": exchange,
            "async_k": async_k,
            "partitioned": bool(args.partition),
            "edge_cut_pct": edge_cut_pct,
        }
        ex = stats_m.extra.get("exchange")
        if ex is not None:
            # The achieved-traffic report (parallel/engine_sharded.
            # _achieved_exchange_report): modeled dense vs achieved
            # delta words/tick, buffer occupancy, overflow counts.
            row["exchange"] = ex
        log(f"{leg_name}: ring {ring['bytes_per_chip']} "
            f"B/chip, wall {wall:.1f}s, parity {parity}"
            + (f", exchange dense={ex.get('modeled_dense_words_per_tick')}"
               f" delta~{ex.get('achieved_delta_words_per_tick', 0):.1f}"
               f" words/tick (occ "
               f"{ex.get('delta_occupancy', 0):.3f})"
               if ex is not None and ex.get("mode") in ("delta", "hub")
               else ""))
        emit(row)

    # Every pair of legs must agree — a check that costs nothing (all
    # already ran) and survives --skip-parity, so even 1M rehearsals
    # certify layout- and wire-format-independence. Sync legs and the
    # K=1 async anchor agree bitwise per tick; K>=2 async legs shift
    # per-tick timing by contract, so they are held to the fixed point
    # instead (equal counters + final coverage row).
    name0, st0, cov0, _ = mesh_runs[0]
    strict = [r for r in mesh_runs[1:] if r[3] <= 1]
    loose = [r for r in mesh_runs[1:] if r[3] >= 2]
    for name_i, st_i, cov_i, _ in strict:
        assert st0.equal_counts(st_i), (
            f"legs disagree on counters: {name0} vs {name_i}"
        )
        assert np.array_equal(cov0, cov_i), (
            f"legs disagree on coverage: {name0} vs {name_i}"
        )
    for name_i, st_i, cov_i, _ in loose:
        assert st0.equal_counts(st_i), (
            f"async leg disagrees on final counters: {name0} vs {name_i}"
        )
        assert np.array_equal(
            np.asarray(cov0)[-1], np.asarray(cov_i)[-1]
        ), f"async leg disagrees on final coverage: {name0} vs {name_i}"
    log("mesh legs bitwise-equal (counters + coverage): "
        + " == ".join(name for name, _, _, k in mesh_runs if k <= 1)
        + ("" if not loose else
           "; async fixed-point-equal: "
           + " == ".join(name for name, _, _, _ in loose)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
