"""Multi-chip 1M-mechanics rehearsal on a virtual CPU mesh (VERDICT r2 #6).

Multi-chip TPU hardware is not attached in this environment, so perf
cannot be measured — but the full BASELINE config-5 *mechanics* can be
proven end-to-end at real scale on an 8-virtual-device CPU mesh: build a
>=100K-node graph, run the sharded flood-coverage engine with lognormal
per-edge delay lines under BOTH history-ring layouts, and check

  - bitwise counter + coverage parity against the single-device engine,
  - per-chip ring bytes scale 1/shards in sharded mode,

so the only untested step to a physical v5e-8 is the hardware itself.

Emits one JSON row per (N, ring_mode) on stdout; diagnostics on stderr.
Usage: python scripts/mesh_rehearsal.py [--nodes 100000] [--prob 0.001]
       [--shares 64] [--devices 8] [--skip-parity]
"""

import argparse
import json
import os
import sys
import time

# Self-locate (PYTHONPATH must stay off the repo — scale_1m.py header).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=100_000)
    ap.add_argument("--prob", type=float, default=0.001)
    ap.add_argument("--shares", type=int, default=64)
    ap.add_argument("--horizon", type=int, default=48)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--block", type=int, default=8)
    ap.add_argument(
        "--delay-max-ticks", type=int, default=4,
        help="lognormal delay cap (distinct delay values L <= cap)",
    )
    ap.add_argument(
        "--skip-parity", action="store_true",
        help="skip the single-device parity run (halves the wall time); "
        "counter conservation is still checked on the sharded run",
    )
    ap.add_argument(
        "--cache", type=str, default="",
        help="npz graph cache, interoperable with scale_1m.py --cache "
        "(same fingerprint scheme) — at N=1M the ER build is ~3.5 min, "
        "so the rehearsal reuses the north-star script's graph",
    )
    args = ap.parse_args()

    # Virtual mesh: this is a mechanics rehearsal, so CPU is the point —
    # pin it before jax loads and fan the host out to N devices.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={args.devices}"
        ).strip()
    from p2p_gossip_tpu.utils.platform import force_cpu_backend_if_requested

    force_cpu_backend_if_requested()

    import jax
    import numpy as np

    import p2p_gossip_tpu as pg
    from p2p_gossip_tpu.engine.sync import run_flood_coverage
    from p2p_gossip_tpu.models.latency import lognormal_delays
    from p2p_gossip_tpu.parallel.engine_sharded import (
        run_sharded_flood_coverage,
    )
    from p2p_gossip_tpu.parallel.mesh import make_mesh
    from p2p_gossip_tpu.runtime import native

    devices = jax.devices("cpu")
    assert len(devices) >= args.devices, devices
    mesh = make_mesh(args.devices, 1, devices=devices[: args.devices])

    # Cache protocol shared with scale_1m.py (same fingerprint, same
    # load/validate/build/save semantics), so /tmp/er1m.npz built by
    # either script serves both.
    from p2p_gossip_tpu.models.topology import load_or_build_graph_cache

    def build():
        graph = native.native_erdos_renyi(
            args.nodes, args.prob, seed=args.seed
        )
        if graph is None:
            graph = pg.erdos_renyi(args.nodes, args.prob, seed=args.seed)
        return graph

    t0 = time.perf_counter()
    graph = load_or_build_graph_cache(
        args.cache, topology="er", nodes=args.nodes, prob=args.prob,
        ba_m=3, seed=args.seed, build=build, log=log,
    )
    log(
        f"graph: N={graph.n} edges={graph.num_edges} dmax={graph.max_degree}"
        f" ({time.perf_counter() - t0:.1f}s)"
    )
    delays = lognormal_delays(
        graph, mean_ticks=2.0, sigma=0.6, max_ticks=args.delay_max_ticks,
        seed=args.seed,
    )
    n_delay_values = len(np.unique(delays[graph.ell()[1]]))
    rng = np.random.default_rng(args.seed)
    origins = rng.integers(0, graph.n, args.shares).astype(np.int32)

    cov_single = None
    if not args.skip_parity:
        t0 = time.perf_counter()
        stats_1, cov_single = run_flood_coverage(
            graph, origins, args.horizon, ell_delays=delays, block=args.block,
        )
        log(f"single-device run: {time.perf_counter() - t0:.1f}s")

    for ring_mode in ("replicated", "sharded"):
        t0 = time.perf_counter()
        stats_m, cov_m = run_sharded_flood_coverage(
            graph, origins, args.horizon, mesh, ell_delays=delays,
            block=args.block, ring_mode=ring_mode,
        )
        wall = time.perf_counter() - t0
        ring = stats_m.extra["ring"]
        # Conservation holds whether or not the parity leg ran — at N=1M
        # the single-device comparison is prohibitive on the host, but
        # received==forwarded / sent==(gen+fwd)*degree still certify the
        # sharded counters.
        stats_m.check_conservation()
        parity = None
        if cov_single is not None:
            parity = bool(
                np.array_equal(cov_single, cov_m)
                and stats_m.equal_counts(stats_1)
            )
            assert parity, f"mesh diverges from single-device ({ring_mode})"
        row = {
            "rehearsal": "sharded_flood_coverage",
            "nodes": graph.n,
            "edges": graph.num_edges,
            "devices": args.devices,
            "shares": args.shares,
            "delay_values": int(n_delay_values),
            "ring_mode": ring["mode"],
            "ring_slots": ring["slots"],
            "ring_bytes_per_chip": ring["bytes_per_chip"],
            "coverage_final_min": int(np.asarray(cov_m)[-1].min()),
            "parity_vs_single_device": parity,
            "wall_s": round(wall, 1),
        }
        log(f"{ring_mode}: ring {ring['bytes_per_chip']} B/chip, "
            f"wall {wall:.1f}s, parity {parity}")
        print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
