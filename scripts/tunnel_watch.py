"""Repo-owned TPU-tunnel watcher (VERDICT round-3 item #1).

Three rounds of on-chip evidence were lost because the thing that fired
the battery lived in the builder's session: when the session died, a
tunnel-up window at 3am was lost with it. This script IS the trap,
committed to the repo, runnable by cron/nohup with no builder attached:

  * every --interval seconds it runs THE device probe
    (p2p_gossip_tpu.utils.platform.run_device_probe — the same probe the
    battery's health gate and wait_for_device use), in a killable
    subprocess with repo entries filtered from PYTHONPATH;
  * every probe — success or failure — appends one JSON line to the
    audit log (docs/artifacts/watch.log by default), fsync'd, so even a
    round with zero tunnel uptime leaves proof the trap was armed;
  * on the first healthy probe it execs scripts/onchip_battery.py (full
    battery, value-first stage order, per-stage JSONL artifacts) and logs
    the battery's exit code;
  * a battery that exits nonzero (tunnel wedged mid-run, failed stage)
    puts the watcher back into probe mode after a cooldown, up to
    --max-fires total battery attempts — every fire passes --skip-done,
    so a re-fire only runs stages whose latest artifact record is not
    ok, never repeating succeeded heavy stages;
  * a battery that exits 0 writes a `battery.done` latch next to the
    audit log: later watcher starts (cron fires, fresh nohup loops)
    exit immediately instead of re-running the whole battery every
    probe interval. Delete the latch to force a fresh battery.

Run it for a round (the driver's wall clock is ~12h):

  nohup python scripts/tunnel_watch.py --max-hours 11 \
      >> docs/artifacts/watch.out 2>&1 &

or from cron (idempotent via the pid file — a second copy exits):

  */20 * * * * cd /root/repo && python scripts/tunnel_watch.py --oneshot

--oneshot mode does a single probe (plus battery fire on success) and
exits, so cron owns the cadence; the default mode owns its own loop.
"""

import argparse
import json
import os
import subprocess
import sys
import time
from datetime import datetime, timezone

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(REPO, "scripts")
# For the lazy `from onchip_battery import STAGE_ORDER` (latch decision).
sys.path.insert(0, SCRIPTS)
DEFAULT_LOG = os.path.join(REPO, "docs", "artifacts", "watch.log")


def pid_path(log_path: str) -> str:
    """Pid file lives next to the audit log (tests point the log at a tmp
    dir and must not leave pid files in the real docs/artifacts)."""
    return os.path.join(os.path.dirname(os.path.abspath(log_path)),
                        "watch.pid")


def done_path(log_path: str) -> str:
    """Completion latch next to the audit log: written only when a
    battery exits 0 AND its summary covers every canonical stage (a
    --stages subset must not block future fires for the stages it never
    ran). Checked before every probe — without it the documented cron
    --oneshot line would re-fire the full multi-hour battery every 20
    minutes for the rest of the round. To force a fresh battery, delete
    THIS file (deleting stage records alone does nothing: this check
    runs before any probe)."""
    return os.path.join(os.path.dirname(os.path.abspath(log_path)),
                        "battery.done")


def filtered_env() -> dict:
    """Probe/battery subprocess env — platform.tunnel_safe_env (repo
    entries filtered from PYTHONPATH; the rationale lives there), shared
    with the battery's stage_env so the rule cannot drift."""
    from p2p_gossip_tpu.utils.platform import tunnel_safe_env

    return tunnel_safe_env()


def log_line(log_path: str, rec: dict) -> None:
    rec = {"utc": datetime.now(timezone.utc).isoformat(timespec="seconds"),
           **rec}
    log_path = os.path.abspath(log_path)  # bare filename → dirname is ""
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    with open(log_path, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())
    print(json.dumps(rec), file=sys.stderr, flush=True)


def probe_once(timeout_s: float) -> tuple[bool, str]:
    from p2p_gossip_tpu.utils.platform import run_device_probe

    return run_device_probe(timeout_s, env=filtered_env())


def fire_battery(log_path: str, battery_budget_s: float,
                 extra_args: list[str], hb_path: str | None = None,
                 stall_after_s: float = 900.0) -> tuple[int, dict]:
    """Run the full battery as a subprocess; its own artifacts land in
    docs/artifacts/battery_*.jsonl. Returns (exit code, parsed summary
    JSON or {}) — rc is -1 on watcher-side timeout (the battery budgets
    its own stages, so this outer budget only catches a hung battery
    process).

    While the battery runs, the watcher polls the stage heartbeat file
    (telemetry/progress.py — the battery exports P2P_HEARTBEAT to every
    stage): a beat written by THIS run that then goes silent for
    ``stall_after_s`` logs a ``battery_stall`` record with the last
    payload (chunk, ticks, coverage), and a later fresh beat logs
    ``battery_stall_recovered``. Observation only — the battery's own
    per-stage budgets do the killing; the stall records exist so the
    audit log says where a long stage sat, live, instead of after the
    fact. The summary feeds the latch decision: a --stages subset or a
    --smoke run must not latch completion."""
    import tempfile

    from p2p_gossip_tpu.telemetry import progress

    argv = [sys.executable, os.path.join(SCRIPTS, "onchip_battery.py"),
            *extra_args]
    log_line(log_path, {"event": "battery_start", "argv": argv})
    t0 = time.monotonic()
    wall_t0 = time.time()

    with tempfile.TemporaryFile(mode="w+") as out_f, \
            tempfile.TemporaryFile(mode="w+") as err_f:
        proc = subprocess.Popen(
            argv, stdout=out_f, stderr=err_f, text=True,
            env=filtered_env(), cwd=REPO,
        )
        deadline = time.monotonic() + battery_budget_s
        stalled = False
        timed_out = False
        while proc.poll() is None:
            if time.monotonic() >= deadline:
                timed_out = True
                proc.kill()
                proc.wait()
                break
            time.sleep(min(30.0, max(1.0, deadline - time.monotonic())))
            if not hb_path:
                continue
            age = progress.heartbeat_age_s(hb_path)
            # Only a beat from THIS battery counts: a leftover file from
            # an earlier run is always "stale" and would fire instantly.
            this_run = age is not None and (time.time() - age) >= wall_t0
            now_stalled = this_run and age > stall_after_s
            if now_stalled and not stalled:
                log_line(log_path, {
                    "event": "battery_stall",
                    "hb_age_s": round(age, 1),
                    "last_beat": progress.read_heartbeat(hb_path) or {},
                })
            elif stalled and this_run and not now_stalled:
                log_line(log_path, {
                    "event": "battery_stall_recovered",
                    "hb_age_s": round(age, 1),
                })
            stalled = now_stalled
        rc = -1 if timed_out else proc.returncode
        out_f.seek(0)
        err_f.seek(0)
        stdout_text = out_f.read()
        err_tail = err_f.read()
    if timed_out:
        # Salvage whatever the battery printed before the kill — a failed
        # battery with no recorded reason defeats this script's purpose.
        tail = ("watcher-side battery budget expired | partial stdout: "
                + stdout_text[-500:])
    else:
        tail = (stdout_text.strip().splitlines() or [""])[-1]
    log_line(log_path, {
        "event": "battery_done", "rc": rc,
        "wall_s": round(time.monotonic() - t0, 1), "summary": tail[-2000:],
        "stderr_tail": err_tail[-2000:],
    })
    summary: dict = {}
    try:
        parsed = json.loads(tail)
        if isinstance(parsed, dict):
            summary = parsed
    except json.JSONDecodeError:
        pass
    return rc, summary


def other_instance_alive(log_path: str) -> bool:
    """True when the pid file points at a live tunnel_watch process (cron
    idempotency). The cmdline check matters: a stale pid recycled by an
    unrelated long-lived process would otherwise disarm every future
    cron fire — the exact lost-evidence failure this script prevents."""
    try:
        with open(pid_path(log_path)) as f:
            pid = int(f.read().strip())
        if pid == os.getpid():
            return False
        os.kill(pid, 0)
    except (OSError, ValueError):
        return False
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmdline = f.read().decode(errors="replace")
        return "tunnel_watch" in cmdline
    except OSError:
        # No /proc (non-Linux): fall back to trusting the live pid.
        return True


def write_pid(log_path: str) -> None:
    path = pid_path(log_path)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(str(os.getpid()))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=1200.0,
                    help="seconds between probes (default 20 min)")
    ap.add_argument("--probe-timeout", type=float, default=150.0,
                    help="per-probe subprocess timeout")
    ap.add_argument("--max-hours", type=float, default=0.0,
                    help="stop watching after this many hours (0 = forever)")
    ap.add_argument("--max-fires", type=int, default=3,
                    help="max battery attempts before the watcher retires")
    ap.add_argument("--battery-budget", type=float, default=6 * 3600.0,
                    help="outer wall budget for one battery run (seconds)")
    ap.add_argument("--cooldown", type=float, default=1800.0,
                    help="wait after a failed battery before re-probing")
    ap.add_argument("--log", default=os.environ.get("P2P_WATCH_LOG",
                                                    DEFAULT_LOG))
    ap.add_argument("--oneshot", action="store_true",
                    help="one probe (+ battery on success), then exit — "
                    "for cron-owned cadence")
    ap.add_argument("--battery-args", default="",
                    help="extra args passed through to onchip_battery.py, "
                    "space-separated (e.g. '--stages bench,kernel')")
    ap.add_argument("--heartbeat",
                    default=os.path.join(
                        os.environ.get(
                            "P2P_BATTERY_DIR",
                            os.path.join(REPO, "docs", "artifacts")),
                        "heartbeat.json"),
                    help="stage heartbeat file to watch for stalls "
                    "(matches onchip_battery.py's P2P_HEARTBEAT)")
    ap.add_argument("--stall-after", type=float, default=900.0,
                    help="log a battery_stall record when this battery's "
                    "heartbeat goes silent this many seconds")
    args = ap.parse_args()

    if os.path.exists(done_path(args.log)):
        log_line(args.log, {"event": "skip",
                            "reason": "battery already complete "
                            f"({done_path(args.log)} exists)"})
        return 0
    if other_instance_alive(args.log):
        log_line(args.log, {"event": "skip", "reason": "instance alive"})
        return 0
    if os.path.exists(pid_path(args.log)):
        # A dead watcher (killed session, OOM) leaves its pid file behind;
        # other_instance_alive already proved nothing live owns it, so
        # clear it here with an audit record instead of requiring the
        # manual `rm -f` the session-bootstrap snippet used to carry.
        log_line(args.log, {"event": "stale_pid_cleared",
                            "path": pid_path(args.log)})
        try:
            os.unlink(pid_path(args.log))
        except OSError:
            pass
    write_pid(args.log)
    try:
        return watch_loop(args)
    finally:
        # A lingering pid file + recycled pid would silently disarm every
        # future cron fire; best-effort removal on every exit path.
        try:
            os.unlink(pid_path(args.log))
        except OSError:
            pass


def watch_loop(args) -> int:
    extra = [a for a in args.battery_args.split() if a]
    # Re-fires must not repeat succeeded heavy stages: the battery's
    # latest-record-wins resume keeps the scarce tunnel-up window for
    # what a wedge actually skipped or failed.
    if "--skip-done" not in extra:
        extra = ["--skip-done", *extra]
    deadline = (time.monotonic() + args.max_hours * 3600.0
                if args.max_hours > 0 else None)
    fires = 0
    log_line(args.log, {
        "event": "watch_start", "pid": os.getpid(),
        "interval_s": args.interval, "oneshot": args.oneshot,
        "max_hours": args.max_hours,
    })
    while True:
        ok, err = probe_once(args.probe_timeout)
        # The heartbeat age rides every probe line: one grep of the audit
        # log then shows tunnel health AND stage liveness side by side.
        from p2p_gossip_tpu.telemetry import progress

        hb_age = progress.heartbeat_age_s(args.heartbeat)
        log_line(args.log, {"event": "probe", "ok": ok,
                            "err": err if not ok else "",
                            "hb_age_s": (round(hb_age, 1)
                                         if hb_age is not None else None)})
        if ok:
            fires += 1
            rc, summary = fire_battery(args.log, args.battery_budget, extra,
                                       hb_path=args.heartbeat,
                                       stall_after_s=args.stall_after)
            if rc == 0:
                from onchip_battery import STAGE_ORDER

                covered = set(summary.get("stages", {}))
                if summary.get("smoke"):
                    # CPU smoke evidence must never disarm the trap.
                    reason = "battery smoke ok; no completion latch"
                elif covered >= set(STAGE_ORDER):
                    # Latch completion so later watcher starts (cron
                    # fires, fresh nohup loops) don't re-run the full
                    # battery. Only for FULL coverage: latching a
                    # --stages subset would permanently block the
                    # stages it never ran.
                    with open(done_path(args.log), "w") as f:
                        f.write(datetime.now(timezone.utc).isoformat(
                            timespec="seconds") + "\n")
                    reason = "battery complete"
                else:
                    reason = (f"battery subset ok ({sorted(covered)}); "
                              "no completion latch")
                log_line(args.log, {"event": "watch_done",
                                    "reason": reason})
                return 0
            if args.oneshot or fires >= args.max_fires:
                log_line(args.log, {"event": "watch_done",
                                    "reason": f"battery rc={rc} after "
                                    f"{fires} fire(s)"})
                return 1
            # Battery failed partway (wedge / failed stage): the tunnel
            # needs its ~1h recovery before a re-probe can succeed, so a
            # longer-than-interval cooldown here wastes nothing.
            sleep_s = max(args.interval, args.cooldown)
        else:
            sleep_s = args.interval
        if args.oneshot:
            return 1
        if deadline is not None and time.monotonic() >= deadline:
            log_line(args.log, {"event": "watch_done",
                                "reason": "max-hours reached"})
            return 1
        if deadline is not None:
            sleep_s = min(sleep_s, max(1.0, deadline - time.monotonic()))
        time.sleep(sleep_s)


if __name__ == "__main__":
    sys.exit(main())
