"""Campaign sweep runner — one JSON line per grid cell on stdout, plus a
human-readable campaign report on stderr (so piping stdout to a file or
`jq` stays clean). Renders on host CPU with no TPU attached; on-chip runs
just inherit the default device.

    python scripts/sweep.py --sweep examples/sweep_small.json
    python scripts/sweep.py --example            # built-in small spec
    python scripts/sweep.py --sweep spec.json --out campaign.jsonl \
        --batch-size 8 --mesh-shards 4 --compare-sequential

``--compare-sequential`` additionally times the first push cell's seed
ensemble as N sequential solo engine runs and records the one-jit
campaign's end-to-end speedup in that cell's JSON (the compile-
amortization + batching win the subsystem exists to deliver).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from p2p_gossip_tpu.utils.platform import force_cpu_backend_if_requested


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _compare_sequential(record: dict) -> dict | None:
    """Time the record's cell as sequential solo engine runs and report
    the campaign's end-to-end advantage, against BOTH baselines:

    - ``sequential_wall_s`` — one solo run per seed with the jit cache
      cleared between runs: the repo's documented status quo ("exactly
      one (topology, seed, config) per process"), each run paying its
      own compile. This is the compile-amortization comparison and the
      headline ``speedup_vs_sequential``.
    - ``warm_loop_wall_s`` — the same loop sharing one compile and one
      staged graph (the best a hand-rolled python loop achieves). The
      campaign's wall INCLUDES its own compile, so this ratio is the
      strictest same-process reading.
    """
    import jax
    import numpy as np

    from p2p_gossip_tpu.batch.sweep import _build_graph, _cell_loss
    from p2p_gossip_tpu.engine.sync import DeviceGraph, run_flood_coverage
    from p2p_gossip_tpu.models.churn import random_churn

    cell = {**record["cell"]}
    cell.setdefault("baseSeed", record["seeds"][0])
    if cell["protocol"] != "push":
        return None
    graph = _build_graph(cell)
    dg = DeviceGraph.build(graph)
    loss = _cell_loss(cell)

    def solo(seed):
        origins = (
            np.random.default_rng(int(seed))
            .integers(0, graph.n, cell["shares"])
            .astype(np.int32)
        )
        churn = (
            random_churn(
                graph.n, cell["horizon"], outage_prob=cell["churnProb"],
                mean_down_ticks=10.0, seed=int(seed) + 7919,
            )
            if cell["churnProb"] > 0.0
            else None
        )
        run_flood_coverage(
            graph, origins, cell["horizon"], churn=churn, loss=loss,
            device_graph=dg,
        )

    t0 = time.perf_counter()
    for seed in record["seeds"]:
        jax.clear_caches()  # one-config-per-process semantics
        solo(seed)
    seq_fresh = time.perf_counter() - t0
    solo(record["seeds"][0])  # compile once outside the timed warm loop
    t0 = time.perf_counter()
    for seed in record["seeds"]:
        solo(seed)
    seq_warm = time.perf_counter() - t0

    camp_wall = record["summary"]["wall_s"]
    return {
        "sequential_wall_s": round(seq_fresh, 4),
        "warm_loop_wall_s": round(seq_warm, 4),
        "campaign_wall_s": camp_wall,
        "speedup_vs_sequential": round(seq_fresh / max(camp_wall, 1e-9), 2),
        "speedup_vs_warm_loop": round(seq_warm / max(camp_wall, 1e-9), 2),
        "replicas": len(record["seeds"]),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sweep", type=str, default="", help="sweep spec JSON path")
    ap.add_argument(
        "--example", action="store_true",
        help="run the built-in small example spec (batch.sweep.example_spec)",
    )
    ap.add_argument(
        "--out", type=str, default="",
        help="also append the JSON records to this file (one line each)",
    )
    ap.add_argument(
        "--batch-size", type=int, default=0,
        help="static replica batch size (0 = all replicas in one batch)",
    )
    ap.add_argument(
        "--mesh-shards", type=int, default=0,
        help="shard the replica axis over this many devices (0 = no mesh)",
    )
    ap.add_argument(
        "--compare-sequential", action="store_true",
        help="time the first push cell as sequential solo runs and record "
        "the campaign speedup in its JSON",
    )
    ap.add_argument(
        "--no-report", action="store_true",
        help="suppress the human-readable report (JSON lines only)",
    )
    args = ap.parse_args()

    force_cpu_backend_if_requested()
    if args.example:
        from p2p_gossip_tpu.batch.sweep import example_spec

        spec = example_spec()
    elif args.sweep:
        with open(args.sweep, encoding="utf-8") as f:
            spec = json.load(f)
    else:
        ap.error("pass --sweep <spec.json> or --example")

    from p2p_gossip_tpu.batch.stats import format_campaign_report
    from p2p_gossip_tpu.batch.sweep import run_sweep

    mesh = None
    if args.mesh_shards:
        from p2p_gossip_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(1, args.mesh_shards)
        log(f"mesh: replica axis over {args.mesh_shards} device(s)")

    out_f = open(args.out, "a", encoding="utf-8") if args.out else None

    def emit(record):
        line = json.dumps(record)
        print(line, flush=True)
        if out_f:
            out_f.write(line + "\n")
            out_f.flush()

    try:
        records = run_sweep(
            spec, batch_size=args.batch_size or None, mesh=mesh, emit=emit
        )
    finally:
        if out_f:
            out_f.close()

    if args.compare_sequential:
        for record in records:
            cmp = _compare_sequential(record)
            if cmp is not None:
                record["compare_sequential"] = cmp
                # stderr + --out only: stdout stays one line per cell.
                log(
                    f"compare-sequential: {cmp['replicas']} solo runs "
                    f"{cmp['sequential_wall_s']:.2f}s (per-run compile; "
                    f"warm loop {cmp['warm_loop_wall_s']:.2f}s) vs campaign "
                    f"{cmp['campaign_wall_s']:.2f}s = "
                    f"{cmp['speedup_vs_sequential']:.2f}x "
                    f"({cmp['speedup_vs_warm_loop']:.2f}x vs warm loop)"
                )
                if args.out:
                    with open(args.out, "a", encoding="utf-8") as f:
                        f.write(json.dumps({"compare_sequential": cmp}) + "\n")
                break

    if not args.no_report:
        log(format_campaign_report(records))
    return 0


if __name__ == "__main__":
    sys.exit(main())
