"""Campaign sweep runner — one JSON line per grid cell on stdout, plus a
human-readable campaign report on stderr (so piping stdout to a file or
`jq` stays clean). Renders on host CPU with no TPU attached; on-chip runs
just inherit the default device.

    python scripts/sweep.py --sweep examples/sweep_small.json
    python scripts/sweep.py --example            # built-in small spec
    python scripts/sweep.py --sweep spec.json --out campaign.jsonl \
        --batch-size 8 --mesh-shards 4 --compare-sequential

``--compare-sequential`` additionally times the first cell of EACH
protocol's seed ensemble as N sequential solo engine runs and records
the one-jit campaign's end-to-end speedup (the compile-amortization +
batching win the subsystem exists to deliver). Each comparison is also
printed to stdout as its own JSON line (``{"compare_sequential": ...}``)
so artifact consumers that parse stdout — the on-chip battery — capture
it alongside the cell records.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from p2p_gossip_tpu.utils.platform import force_cpu_backend_if_requested


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _compare_sequential(record: dict) -> dict | None:
    """Time the record's cell as sequential solo engine runs and report
    the campaign's end-to-end advantage, against BOTH baselines:

    - ``sequential_wall_s`` — one solo run per seed with the jit cache
      cleared between runs: the repo's documented status quo ("exactly
      one (topology, seed, config) per process"), each run paying its
      own compile. This is the compile-amortization comparison and the
      headline ``speedup_vs_sequential``.
    - ``warm_loop_wall_s`` — the same loop sharing one compile and one
      staged graph (the best a hand-rolled python loop achieves). The
      campaign's wall INCLUDES its own compile, so this ratio is the
      strictest same-process reading.

    Partnered protocols (pushpull/pull/pushk) compare against the sweep's
    pre-vmap sequential engine (`_run_partnered_cell`, verbatim) and also
    record ``campaign_warm_wall_s`` — a warm re-run of the vmapped cell
    (jit cache hot), the steady-state number a multi-cell sweep actually
    pays — plus its ``speedup_warm_vs_warm_loop``.
    """
    import jax
    import numpy as np

    from p2p_gossip_tpu.batch.sweep import _DEFAULTS, _build_graph, _cell_loss
    from p2p_gossip_tpu.engine.sync import DeviceGraph, run_flood_coverage
    from p2p_gossip_tpu.models.churn import random_churn
    from p2p_gossip_tpu.models.seeds import churn_stream_seed

    # The record's cell dict carries only the reported keys; restore the
    # sweep defaults for the ones it omits (churn knobs, baseSeed).
    cell = {**_DEFAULTS, **record["cell"]}
    cell["baseSeed"] = record["cell"].get("baseSeed", record["seeds"][0])
    graph = _build_graph(cell)
    loss = _cell_loss(cell)
    camp_wall = record["summary"]["wall_s"]

    if cell["protocol"] != "push":
        from p2p_gossip_tpu.batch.campaign import (
            flood_replicas,
            run_protocol_campaign,
        )
        from p2p_gossip_tpu.batch.sweep import _run_partnered_cell

        seeds = np.asarray(record["seeds"], dtype=np.int64)
        replicas = flood_replicas(
            graph, cell["shares"], seeds, cell["horizon"],
            churn_prob=cell["churnProb"],
            mean_down_ticks=cell["churnDowntimeTicks"],
            max_outages=cell["churnOutages"],
        )

        def campaign_once():
            run_protocol_campaign(
                graph, replicas, cell["horizon"], protocol=cell["protocol"],
                fanout=cell["fanout"], loss=loss,
            )

        # Prime the compile unconditionally: an earlier protocol's fresh
        # loop clear_caches()d the jit cache, so "cache hot from
        # run_cell" cannot be assumed.
        campaign_once()
        t0 = time.perf_counter()
        campaign_once()
        camp_warm = time.perf_counter() - t0
        # Warm loop: the pre-vmap sequential engine, one compile shared.
        _run_partnered_cell(cell, graph, seeds[:1], loss)
        t0 = time.perf_counter()
        _run_partnered_cell(cell, graph, seeds, loss)
        seq_warm = time.perf_counter() - t0
        # Fresh (per-run compile), sampled and extrapolated to keep the
        # comparison wall sane — labeled via sequential_sampled.
        sample = min(4, len(seeds))
        t0 = time.perf_counter()
        for s in seeds[:sample]:
            jax.clear_caches()
            _run_partnered_cell(cell, graph, np.asarray([s]), loss)
        seq_fresh = (time.perf_counter() - t0) * (len(seeds) / sample)
        return {
            "sequential_wall_s": round(seq_fresh, 4),
            "sequential_sampled": sample,
            "warm_loop_wall_s": round(seq_warm, 4),
            "campaign_wall_s": camp_wall,
            "campaign_warm_wall_s": round(camp_warm, 4),
            "speedup_vs_sequential": round(seq_fresh / max(camp_wall, 1e-9), 2),
            "speedup_vs_warm_loop": round(seq_warm / max(camp_wall, 1e-9), 2),
            "speedup_warm_vs_warm_loop": round(
                seq_warm / max(camp_warm, 1e-9), 2
            ),
            "replicas": len(record["seeds"]),
        }

    dg = DeviceGraph.build(graph)

    def solo(seed):
        origins = (
            np.random.default_rng(int(seed))
            .integers(0, graph.n, cell["shares"])
            .astype(np.int32)
        )
        churn = (
            random_churn(
                graph.n, cell["horizon"], outage_prob=cell["churnProb"],
                mean_down_ticks=10.0, seed=churn_stream_seed(seed),
            )
            if cell["churnProb"] > 0.0
            else None
        )
        run_flood_coverage(
            graph, origins, cell["horizon"], churn=churn, loss=loss,
            device_graph=dg,
        )

    t0 = time.perf_counter()
    for seed in record["seeds"]:
        jax.clear_caches()  # one-config-per-process semantics
        solo(seed)
    seq_fresh = time.perf_counter() - t0
    solo(record["seeds"][0])  # compile once outside the timed warm loop
    t0 = time.perf_counter()
    for seed in record["seeds"]:
        solo(seed)
    seq_warm = time.perf_counter() - t0

    return {
        "sequential_wall_s": round(seq_fresh, 4),
        "warm_loop_wall_s": round(seq_warm, 4),
        "campaign_wall_s": camp_wall,
        "speedup_vs_sequential": round(seq_fresh / max(camp_wall, 1e-9), 2),
        "speedup_vs_warm_loop": round(seq_warm / max(camp_wall, 1e-9), 2),
        "replicas": len(record["seeds"]),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sweep", type=str, default="", help="sweep spec JSON path")
    ap.add_argument(
        "--example", action="store_true",
        help="run the built-in small example spec (batch.sweep.example_spec)",
    )
    ap.add_argument(
        "--out", type=str, default="",
        help="also append the JSON records to this file (one line each)",
    )
    ap.add_argument(
        "--batch-size", type=int, default=0,
        help="static replica batch size (0 = all replicas in one batch)",
    )
    ap.add_argument(
        "--mesh-shards", type=int, default=0,
        help="shard the replica axis over this many devices (0 = no mesh)",
    )
    ap.add_argument(
        "--compare-sequential", action="store_true",
        help="time the first push cell as sequential solo runs and record "
        "the campaign speedup in its JSON",
    )
    ap.add_argument(
        "--no-report", action="store_true",
        help="suppress the human-readable report (JSON lines only)",
    )
    ap.add_argument(
        "--telemetry", type=str, default="",
        help="stream telemetry (per-cell spans + in-jit metric rings) to "
        "this JSONL file; also honors P2P_TELEMETRY (docs/OBSERVABILITY.md)",
    )
    args = ap.parse_args()

    if args.telemetry:
        from p2p_gossip_tpu import telemetry

        telemetry.configure(args.telemetry, rings=True)

    force_cpu_backend_if_requested()
    # Same contract as bench.py: a wedged tunnel must not hang the run
    # in backend init — probe it in killable subprocesses and fall back
    # to a CPU run (honestly labeled via each record's `platform`) if
    # the device never answers. The on-chip battery's campaign stage
    # rides this path.
    from p2p_gossip_tpu.utils.platform import (
        cpu_requested,
        wait_for_device,
    )

    if not cpu_requested():
        try:
            wait_for_device()
        except Exception as e:
            log(
                f"device unreachable ({type(e).__name__}); running the "
                "sweep on CPU (records stay platform-labeled)"
            )
            os.environ["JAX_PLATFORMS"] = "cpu"
            force_cpu_backend_if_requested()
    if args.example:
        from p2p_gossip_tpu.batch.sweep import example_spec

        spec = example_spec()
    elif args.sweep:
        with open(args.sweep, encoding="utf-8") as f:
            spec = json.load(f)
    else:
        ap.error("pass --sweep <spec.json> or --example")

    from p2p_gossip_tpu.batch.stats import format_campaign_report
    from p2p_gossip_tpu.batch.sweep import run_sweep

    mesh = None
    if args.mesh_shards:
        from p2p_gossip_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(1, args.mesh_shards)
        log(f"mesh: replica axis over {args.mesh_shards} device(s)")

    out_f = open(args.out, "a", encoding="utf-8") if args.out else None

    def emit(record):
        line = json.dumps(record)
        print(line, flush=True)
        if out_f:
            out_f.write(line + "\n")
            out_f.flush()

    try:
        records = run_sweep(
            spec, batch_size=args.batch_size or None, mesh=mesh, emit=emit
        )
    finally:
        if out_f:
            out_f.close()

    if args.compare_sequential:
        compared: set[str] = set()
        for record in records:
            proto = record["cell"]["protocol"]
            if proto in compared:
                continue
            cmp = _compare_sequential(record)
            if cmp is None:
                continue
            compared.add(proto)
            record["compare_sequential"] = cmp
            log(
                f"compare-sequential [{proto}]: {cmp['replicas']} solo "
                f"runs {cmp['sequential_wall_s']:.2f}s (per-run compile; "
                f"warm loop {cmp['warm_loop_wall_s']:.2f}s) vs campaign "
                f"{cmp['campaign_wall_s']:.2f}s = "
                f"{cmp['speedup_vs_sequential']:.2f}x "
                f"({cmp['speedup_vs_warm_loop']:.2f}x vs warm loop)"
            )
            line = json.dumps(
                {"compare_sequential": {**cmp, "protocol": proto}}
            )
            # stdout too: the battery parses stdout JSON lines, and the
            # comparison is the stage's headline evidence.
            print(line, flush=True)
            if args.out:
                with open(args.out, "a", encoding="utf-8") as f:
                    f.write(line + "\n")

    if not args.no_report:
        log(format_campaign_report(records))
    return 0


if __name__ == "__main__":
    sys.exit(main())
