"""On-chip Pallas-vs-XLA kernel bake-off (VERDICT round-1 item #3).

Measures, on the real TPU, each candidate kernel against its XLA
formulation at bench-relevant shapes, asserting bitwise parity before
timing:

1. coverage_per_slot   — Pallas one-pass kernel vs the jnp bit-expansion
                         (row sweep doubles as the 1M-crash bisection;
                         the fused tick-update kernels that used to be
                         benched between 1 and 2 lost on hardware and
                         were deleted — see docs/RESULTS.md)
2. gather-OR frontier  — the XLA blocked-gather path at several degree
                         blocks (the Pallas rejection arithmetic for a
                         per-edge-DMA formulation is printed alongside:
                         it is not implemented because its descriptor
                         count is prohibitive — see the JSON notes)

Timing discipline: the axon platform executes asynchronously and
`block_until_ready` does NOT block — only a device-to-host transfer
forces execution. Every measurement chains ``iters`` dependent
applications on-device and forces ONE reduced scalar at the end.

Output: one JSON object per line on stdout; progress to stderr.
Usage: python scripts/kernel_bench.py [--rows 100000] [--words 256]
       [--sweep]   (adds the 250K/500K/1M coverage-row bisection)
"""

import argparse
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


#: Stamped into every emitted row once the device is known: CPU rows run
#: Pallas in interpret mode, so their "speedup" numbers are meaningless
#: for the TPU bake-off — they must never be mistaken for on-chip rows.
_ROW_TAG: dict = {}


def emit(**row):
    print(json.dumps({**_ROW_TAG, **row}), flush=True)


def chain_time(fn, x, iters=20):
    """Wall time per op over ``iters`` chained dependent applications,
    forced once via host transfer of a reduction."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def chained(x):
        for _ in range(iters):
            x = fn(x)
        return jnp.sum(x[..., :1])

    np.asarray(chained(x))  # compile + warm
    t0 = time.perf_counter()
    np.asarray(chained(x))
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=100_000)
    ap.add_argument("--words", type=int, default=256)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument(
        "--sweep", action="store_true",
        help="row sweep 250K/500K/1M for the coverage kernel (the round-1 "
        "worker-crash bisection); run each under its own process if the "
        "tunnel is fragile",
    )
    ap.add_argument(
        "--skip-gather", action="store_true",
        help="skip the gather timing (needs a 100K-node graph build)",
    )
    ap.add_argument(
        "--cache", default="",
        help="npz graph cache for the gather graph (scale_1m.py "
        "fingerprint scheme); the RCM permutation persists alongside it "
        "as an aux array, so the host-side reordering runs once per "
        "graph build instead of once per bench invocation",
    )
    from p2p_gossip_tpu.utils.platform import (
        add_cpu_arg,
        apply_cpu_arg,
        wait_for_device,
    )

    add_cpu_arg(ap)
    args = ap.parse_args()
    apply_cpu_arg(args)

    wait_for_device()
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    log(f"device: {dev}")
    on_tpu = dev.platform == "tpu"
    interpret = not on_tpu
    _ROW_TAG["platform"] = dev.platform
    if interpret:
        _ROW_TAG["interpret_mode"] = True

    from p2p_gossip_tpu.ops import bitmask
    from p2p_gossip_tpu.ops.pallas_kernels import coverage_per_slot_pallas

    rng = np.random.default_rng(0)

    def rand_bits(n, w):
        return jnp.asarray(
            rng.integers(0, 2**32, size=(n, w), dtype=np.uint64).astype(
                np.uint32
            )
        )

    # --- 1. coverage kernel --------------------------------------------
    row_list = [args.rows] + ([250_000, 500_000, 1_000_000] if args.sweep else [])
    slots = args.words * 32
    for n in row_list:
        seen = rand_bits(n, args.words)
        want = np.asarray(bitmask.coverage_per_slot(seen, slots))
        got = np.asarray(
            coverage_per_slot_pallas(seen, slots, interpret=interpret)
        )
        assert np.array_equal(want, got), f"coverage parity FAILED at N={n}"
        t_xla = _time_cov(
            lambda s: bitmask.coverage_per_slot(s, slots), seen, args.iters
        )
        t_pal = _time_cov(
            lambda s: coverage_per_slot_pallas(s, slots, interpret=interpret),
            seen, args.iters,
        )
        log(f"coverage N={n}: xla {t_xla*1e3:.2f} ms  pallas {t_pal*1e3:.2f} ms")
        emit(
            kernel="coverage_per_slot", rows=n, words=args.words,
            xla_ms=round(t_xla * 1e3, 3), pallas_ms=round(t_pal * 1e3, 3),
            speedup=round(t_xla / t_pal, 3), parity="ok",
        )

    # --- 2. (removed) fused tick update ------------------------------
    # The fused Pallas tick-update kernels were benched on hardware by
    # the round-4 battery (kernel stage, 2026-07-31): tick_update lost
    # 0.50x and tick_update+coverage 0.60x against the fused XLA graph
    # at 100K x 256 words — XLA already fuses the arrivals->newly->seen->
    # popcount chain better than the hand tiling. Per the enable-or-
    # delete rule the kernels are gone; the A/B rows live in
    # docs/RESULTS.md and docs/artifacts/battery_20260731T031929Z.jsonl.
    emit(
        kernel="tick_update", status="removed",
        note="lost 0.50x on hardware vs fused XLA (round-4 battery); "
        "kernel deleted, XLA path is the product path",
    )
    emit(
        kernel="tick_update_cov", status="removed",
        note="lost 0.60x on hardware vs fused XLA (round-4 battery); "
        "kernel deleted, XLA path is the product path",
    )

    # --- 3. gather-OR (XLA path + the Pallas rejection arithmetic) -----
    if not args.skip_gather:
        import p2p_gossip_tpu as pg
        from p2p_gossip_tpu.engine.sync import DeviceGraph
        from p2p_gossip_tpu.ops.ell import propagate_bucketed

        from p2p_gossip_tpu.models.topology import (
            load_or_build_graph_cache,
        )

        g_rows = min(args.rows, 100_000)
        g = load_or_build_graph_cache(
            args.cache, topology="er", nodes=g_rows, prob=0.001, ba_m=3,
            seed=0, build=lambda: pg.erdos_renyi(g_rows, 0.001, seed=0),
            log=log,
        )
        # bucketed=True unconditionally: small --rows smoke runs fall
        # under the auto threshold but must exercise the same path.
        dg = DeviceGraph.build(g, bucketed=True)
        w = args.words
        hist = rand_bits(2 * g.n, w).reshape(2, g.n, w)
        edges = int(np.asarray(dg.degree).sum())

        def make_gather(blk, dg_=dg, n_out=g.n):
            def gather(h):
                arr = propagate_bucketed(
                    h[0][None], jnp.int32(1), dg_.buckets, n_out=n_out,
                    ring_size=1, uniform_delay=0, block=blk,
                )
                return h ^ arr[None]
            return gather

        # 128 rides along to test whether the round-1 sweep (which chose
        # 64 from {8,16,32,64}) stopped short of the optimum.
        for blk in (8, 32, 64, 128):
            t = chain_time(make_gather(blk), hist, max(args.iters // 2, 5))
            log(f"gather block={blk}: {t*1e3:.2f} ms/tick")
            emit(
                kernel="gather_or_xla", rows=g.n, words=w, block=blk,
                ms_per_tick=round(t * 1e3, 3),
                gathered_gb=round(edges * w * 4 / 1e9, 2),
                achieved_gbps=round(edges * w * 4 / t / 1e9, 1),
            )
        # Why no Pallas gather: a per-edge DMA formulation issues one
        # descriptor per (edge, W-word row); at ~1 us/descriptor issue+
        # latency that alone exceeds the XLA gather's whole-tick time by
        # orders of magnitude.
        frontier_mb = g.n * w * 4 / 1e6
        vmem_note = (
            f"frontier ({g.n}x{w}x4B = {frontier_mb:.1f} MB) cannot be "
            "VMEM-resident (16 MB), so a dense in-VMEM gather is impossible"
            if frontier_mb > 16
            else f"frontier is only {frontier_mb:.1f} MB at this smoke "
            "shape (bench shapes exceed VMEM)"
        )
        emit(
            kernel="gather_or_pallas_rejection", rows=g.n, edges=edges,
            note=(
                "per-edge DMA formulation rejected by arithmetic: "
                f"{edges} descriptors x ~1us >> XLA gather tick; " + vmem_note
            ),
        )

        # Word-width sweep at the tuned block: measures the lane-underfill
        # penalty the MIN_CHUNK_SHARES comment quotes (~15x worse bytes/s
        # at 32 words vs 128, round-1 measurement). The resident-HBM
        # auto-chunk (scale_1m.py) halves the pad to 64 words at the 1M
        # shape, so the 64-vs-128 ratio is exactly the bandwidth price of
        # fitting — worth a measured row, not a two-generations-old quote.
        # All four widths are emitted (the default width repeats its
        # block-sweep measurement) so this table is self-contained.
        for ww in (32, 64, 128, 256):
            hist_w = rand_bits(2 * g.n, ww).reshape(2, g.n, ww)
            t = chain_time(make_gather(64), hist_w, max(args.iters // 2, 5))
            log(f"gather words={ww}: {t*1e3:.2f} ms/tick")
            emit(
                kernel="gather_or_xla_wsweep", rows=g.n, words=ww, block=64,
                ms_per_tick=round(t * 1e3, 3),
                gathered_gb=round(edges * ww * 4 / 1e9, 2),
                achieved_gbps=round(edges * ww * 4 / t / 1e9, 1),
            )

        # RCM-relabeled gather: does clustering neighborhoods in node-id
        # space (= HBM address space for the frontier rows) buy gather
        # bandwidth? Same edges, same degree multiset, bitwise-equal
        # dynamics (tests/test_topology.py) — only the id layout differs.
        # On this ER expander RCM cannot reduce bandwidth much in theory;
        # this row measures what locality is actually worth on the chip
        # before investing in reorder-aware staging.
        try:
            from p2p_gossip_tpu.models.topology import (
                load_or_compute_graph_aux,
                rcm_order,
                relabel_graph,
                scale_graph_fingerprint,
            )

            # The permutation is a pure function of the graph, so it
            # rides the same npz under the build fingerprint and the
            # host-side RCM pass runs once per graph build.
            order = load_or_compute_graph_aux(
                args.cache, "rcm",
                scale_graph_fingerprint("er", g_rows, 0.001, 3, 0),
                lambda: rcm_order(g), log,
            )
            rg, _inv = relabel_graph(g, order)
        except ImportError as e:  # rcm_order needs scipy (optional dep)
            emit(kernel="gather_or_xla_rcm", rows=g.n,
                 note=f"skipped: {e}")
        else:
            dg_r = DeviceGraph.build(rg, bucketed=True)
            t = chain_time(
                make_gather(64, dg_r, rg.n), hist, max(args.iters // 2, 5)
            )
            log(f"gather rcm block=64: {t*1e3:.2f} ms/tick")
            emit(
                kernel="gather_or_xla_rcm", rows=g.n, words=w, block=64,
                ms_per_tick=round(t * 1e3, 3),
                gathered_gb=round(edges * w * 4 / 1e9, 2),
                achieved_gbps=round(edges * w * 4 / t / 1e9, 1),
            )


def _time_cov(fn, seen, iters):
    """Coverage returns (S,) int32 — chain by folding back into uint32."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def chained(s):
        acc = jnp.int32(0)
        for _ in range(iters):
            cov = fn(s)
            acc = acc + cov[0]
            s = s ^ acc.astype(jnp.uint32)  # data dependence
        return acc

    np.asarray(chained(seen))
    t0 = time.perf_counter()
    np.asarray(chained(seen))
    return (time.perf_counter() - t0) / iters


if __name__ == "__main__":
    main()
