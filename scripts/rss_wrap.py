"""Run a command and report its peak RSS — a `/usr/bin/time -v` stand-in
(the image ships no GNU time). Used by the round-4 host-side 1M evidence
runs so RESULTS.md can state peak memory alongside wall clock.

Usage: python scripts/rss_wrap.py CMD [ARG...]

Child stdout/stderr pass through untouched; after the child exits, one
JSON line `{"rss_wrap": {...}}` with peak child RSS (bytes) and wall
seconds is appended to THIS process's stderr, and the child's exit code
is propagated.
"""

import json
import resource
import subprocess
import sys
import time


def main() -> int:
    t0 = time.perf_counter()
    rc = subprocess.call(sys.argv[1:])
    wall = time.perf_counter() - t0
    ru = resource.getrusage(resource.RUSAGE_CHILDREN)
    # Linux reports ru_maxrss in KiB.
    print(
        json.dumps(
            {
                "rss_wrap": {
                    "argv": sys.argv[1:],
                    "rc": rc,
                    "wall_s": round(wall, 1),
                    "peak_rss_bytes": ru.ru_maxrss * 1024,
                    "peak_rss_gib": round(ru.ru_maxrss / 1048576, 2),
                }
            }
        ),
        file=sys.stderr,
        flush=True,
    )
    return rc


if __name__ == "__main__":
    sys.exit(main())
