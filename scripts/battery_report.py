"""Render an on-chip battery artifact (JSONL) as markdown tables.

Closes the last gap between "the battery ran" and "the results are
documented": `onchip_battery.py` persists one JSONL record per stage;
this script turns that file into the markdown sections docs/RESULTS.md
wants (headline bench row, protocol trade-off table, kernel A/B table,
coverage-sweep bisection, 1M north-star lines), so a tunnel-up window
minutes before a deadline still produces paste-ready documentation.

Usage: python scripts/battery_report.py [docs/artifacts/battery_latest.jsonl]
Markdown on stdout; exits 1 if the artifact records any failed stage so
automation can tell a complete battery from a partial one.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Mirror onchip_battery.py's --art-dir resolution (P2P_BATTERY_DIR wins)
# so a no-arg report reads the same battery_latest.jsonl the battery wrote.
DEFAULT = os.path.join(
    os.environ.get(
        "P2P_BATTERY_DIR",
        os.path.join(REPO, "docs", "artifacts"),
    ),
    "battery_latest.jsonl",
)


def md_table(rows: list[dict], cols: list[str]) -> str:
    out = ["| " + " | ".join(cols) + " |",
           "|" + "---|" * len(cols)]
    for r in rows:
        # None (e.g. bench.py's deliberately-null pct_hbm_peak on CPU
        # runs) renders as the same em-dash as a missing key.
        out.append(
            "| "
            + " | ".join(
                "—" if r.get(c) is None else str(r[c]) for c in cols
            )
            + " |"
        )
    return "\n".join(out)


def main() -> int:
    path = sys.argv[1] if len(sys.argv) > 1 else DEFAULT
    truncated = 0
    try:
        with open(path) as f:
            records = []
            for line in f:
                if not line.strip():
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    # A battery killed mid-append leaves a partial final
                    # line; the completed stages must still render —
                    # salvaging partial batteries is this script's job.
                    truncated += 1
    except FileNotFoundError:
        print(f"error: no battery artifact at {path}", file=sys.stderr)
        return 2
    if not records:
        print(f"error: {path} has no complete records", file=sys.stderr)
        return 2
    if truncated:
        print(f"warning: skipped {truncated} truncated record(s) in {path}",
              file=sys.stderr)

    by_stage: dict[str, dict] = {}
    for rec in records:
        by_stage[rec["stage"]] = rec  # later run of a stage wins

    print(f"# On-chip battery report — {records[0]['utc']}\n")
    status_rows = [
        {
            "stage": r["stage"], "rc": r["rc"],
            "wall_s": r["wall_s"], "results": len(r["results"]),
        }
        for r in records
    ]
    print(md_table(status_rows, ["stage", "rc", "wall_s", "results"]))
    print()

    bench_rows = []
    for stage in ("bench", "bench_rep2", "bench_rep3"):
        rec = by_stage.get(stage)
        if rec and rec["results"]:
            bench_rows.append({"stage": stage, **rec["results"][-1]})
    if bench_rows:
        print("## Headline bench\n")
        print(md_table(bench_rows, [
            "stage", "metric", "value", "unit", "vs_baseline",
            "achieved_gbps", "pct_hbm_peak", "ticks",
        ]))
        values = sorted(r["value"] for r in bench_rows)
        if len(values) > 1:
            # The variance line the repeat stages exist for: one number
            # per window can't distinguish drift from noise.
            spread = (values[-1] - values[0]) / values[-1] * 100
            print(
                f"\nacross {len(values)} runs: min {values[0]:.4g}, "
                f"median {values[len(values) // 2]:.4g}, "
                f"max {values[-1]:.4g} ({spread:.1f}% spread)"
            )
        print()

    protocols = by_stage.get("protocols")
    if protocols and protocols["results"]:
        payload = protocols["results"][-1]
        cfg = payload.get("config", {})
        print(
            f"## Protocol comparison (N={cfg.get('nodes')}, "
            f"p={cfg.get('prob')}, {cfg.get('shares')} shares)\n"
        )
        print(md_table(payload.get("results", []), [
            "protocol", "reached_fraction", "final_coverage_mean",
            "ttc_median_ticks", "sends_per_delivery", "total_sent",
            "p95_latency_ticks", "wall_s",
        ]))
        print(
            "\nreached_fraction = shares hitting the 99% coverage bar "
            "within the horizon; final_coverage_mean = mean nodes reached "
            "per share at horizon (rumor mongering trades the last-mile "
            "tail for ~fanout sends per delivery, so a 0.0 bar with high "
            "mean coverage is the protocol's designed trade-off, not a "
            "failure)."
        )
        print()

    camp = by_stage.get("campaign")
    if camp and camp["results"]:
        cells = [r for r in camp["results"] if "cell" in r]
        if cells:
            print("## Campaign engine (vmapped seed ensembles)\n")
            print(md_table(
                [
                    {
                        "protocol": c["cell"].get("protocol"),
                        "engine": c.get("engine"),
                        "platform": c.get("platform"),
                        "replicas": len(c.get("seeds", [])),
                        "lossProb": c["cell"].get("lossProb"),
                        "ttc_p50": ((c.get("summary", {}).get("ttc") or {})
                                    .get("ticks") or {}).get("p50"),
                        "wall_s": c.get("wall_s"),
                    }
                    for c in cells
                ],
                ["protocol", "engine", "platform", "replicas", "lossProb",
                 "ttc_p50", "wall_s"],
            ))
            print()
        cmps = [
            r["compare_sequential"]
            for r in camp["results"]
            if isinstance(r.get("compare_sequential"), dict)
        ]
        if cmps:
            print("## Campaign vs sequential-per-seed\n")
            print(md_table(cmps, [
                "protocol", "replicas", "sequential_wall_s",
                "warm_loop_wall_s", "campaign_wall_s",
                "campaign_warm_wall_s", "speedup_vs_sequential",
                "speedup_vs_warm_loop", "speedup_warm_vs_warm_loop",
            ]))
            print()

    kernel_rows = []
    for stage in ("kernel", "sweep250"):
        rec = by_stage.get(stage)
        if rec:
            for row in rec["results"]:
                kernel_rows.append({"stage": stage, **row})
    if kernel_rows:
        ab = [r for r in kernel_rows if "speedup" in r]
        if ab:
            print("## Kernel A/B (Pallas vs XLA; parity asserted "
                  "before timing)\n")
            print(md_table(ab, [
                "stage", "kernel", "rows", "words", "xla_ms", "pallas_ms",
                "speedup", "parity",
            ]))
            print()
        gather = [r for r in kernel_rows if r.get("kernel") == "gather_or_xla"]
        if gather:
            print("## Gather-OR block sweep (XLA path)\n")
            print(md_table(gather, [
                "rows", "block", "ms_per_tick", "gathered_gb",
                "achieved_gbps",
            ]))
            print()
        wsweep = [
            r for r in kernel_rows if r.get("kernel") == "gather_or_xla_wsweep"
        ]
        if wsweep:
            print("## Gather-OR word-width sweep (block 64)\n")
            print(md_table(wsweep, [
                "rows", "words", "ms_per_tick", "gathered_gb",
                "achieved_gbps",
            ]))
            print()
        rcm = [r for r in kernel_rows if r.get("kernel") == "gather_or_xla_rcm"]
        if rcm:
            print("## Gather-OR with RCM-relabeled graph (block 64)\n")
            print(md_table(rcm, [
                "rows", "words", "ms_per_tick", "gathered_gb",
                "achieved_gbps", "note",
            ]))
            print()

    sc = by_stage.get("staticcheck")
    if sc and sc["results"]:
        rep = sc["results"][-1]
        comp = rep.get("compile") or {}
        comp_entries = comp.get("entries", [])
        print("## Static analysis (jaxpr audit + recompile sentinel + "
              "lint, on-chip compile leg)\n")
        print(md_table([{
            "ok": rep.get("ok"),
            "platform": rep.get("platform"),
            "entries_audited": (rep.get("jaxpr") or {}).get(
                "entries_audited"),
            "lint_files": (rep.get("lint") or {}).get("files_scanned"),
            "sweep_cells": (rep.get("recompile") or {}).get("cells"),
            "compiled_clean": (
                f"{sum(1 for r in comp_entries if r.get('ok'))}/"
                f"{len(comp_entries)}" if comp_entries else None
            ),
            "violations": rep.get("violations_total"),
            "wall_s": rep.get("wall_s"),
        }], [
            "ok", "platform", "entries_audited", "lint_files",
            "sweep_cells", "compiled_clean", "violations", "wall_s",
        ]))
        failed_compiles = [r for r in comp_entries if not r.get("ok")]
        if failed_compiles:
            print("\nentries failing on-chip compile:")
            for r in failed_compiles:
                print(f"- `{r['entry']}`: {r.get('error', '?')}")
        print()

    tel = by_stage.get("telemetry")
    if tel and tel["results"]:
        smokes = [
            r for r in tel["results"] if r.get("kind") == "telemetry_smoke"
        ]
        if smokes:
            s = smokes[-1]
            summ = s.get("summary") or {}
            ring_totals = summ.get("ring_totals") or {}
            total_newly = sum(
                agg.get("newly_infected", 0) for agg in ring_totals.values()
            )
            print("## Telemetry (in-jit metric rings + host spans, "
                  "schema-gated)\n")
            print(md_table([{
                "ok": s.get("ok"),
                "events": summ.get("events"),
                "spans": summ.get("spans"),
                "rings": summ.get("rings"),
                "newly_infected_total": total_newly,
                "expected_receives": s.get("expected_receives"),
                "errors": len(s.get("errors") or []),
            }], [
                "ok", "events", "spans", "rings", "newly_infected_total",
                "expected_receives", "errors",
            ]))
            for err in (s.get("errors") or [])[:5]:
                print(f"- {err}")
            print()

    prof = by_stage.get("profile")
    if prof and prof["results"]:
        summaries = [
            r for r in prof["results"] if r.get("kind") == "profile_summary"
        ]
        if summaries:
            s = summaries[-1]
            # The battery record holds the parse as of run time; the
            # canonical derived artifact is the standalone summary JSON
            # next to the committed capture, which an offline re-parse
            # may have corrected (e.g. the 2x include_infeed_outfeed
            # row double-count fixed 2026-08-01). Prefer it when present.
            # Key the lookup on the stamp (present in every summary,
            # even when the capture was too large to commit and
            # s["capture"] is None); look beside the jsonl being read
            # first, then the capture's repo-relative path.
            stamp = s.get("utc_stamp") or ""
            cap = s.get("capture") or ""
            candidates = []
            if stamp:
                candidates.append(os.path.join(
                    os.path.dirname(os.path.abspath(path)),
                    f"profile_{stamp}_summary.json",
                ))
            if cap.endswith(".xplane.pb.gz"):
                candidates.append(os.path.join(
                    REPO, cap.replace(".xplane.pb.gz", "_summary.json")
                ))
            # Track WHY the standalone summary lost so the caveat can
            # say the true reason (round-5 advisor: "not found" was
            # also printed for unreadable/wrong-shape files).
            from_file = None
            fallback_why = "no candidate paths (summary has no stamp "\
                "and no committed capture)"
            for spath in candidates:
                if not os.path.exists(spath):
                    fallback_why = "standalone summary JSON not found"
                    continue
                try:
                    with open(spath) as f:
                        loaded = json.load(f)
                except (OSError, ValueError):
                    fallback_why = (
                        f"standalone summary not readable as JSON "
                        f"({os.path.basename(spath)})"
                    )
                    continue
                # Valid JSON that isn't a summary dict (hand-edited,
                # future list-of-summaries writer) must fall back,
                # not crash md_table.
                if isinstance(loaded, dict):
                    s = loaded
                    from_file = spath
                    break
                fallback_why = (
                    f"standalone summary is not a summary object "
                    f"({os.path.basename(spath)})"
                )
            print("## Profiler calibration (measured vs modeled HBM)\n")
            if from_file:
                # Provenance marker: a corrected offline reparse must be
                # distinguishable from the battery-time parse by more
                # than the absence of a caveat.
                print("(corrected standalone summary: "
                      f"{os.path.relpath(from_file, REPO)})\n")
            else:
                print(
                    f"(battery-time parse — {fallback_why}; sums may "
                    "predate offline corrections, e.g. the 2026-08-01 "
                    "2x row-double-count fix)\n"
                )
            print(md_table([s], [
                "bench_metric",
                "tool", "op_rows", "ops_with_hbm_bw", "total_self_time_us",
                "measured_hbm_bytes", "measured_hbm_gbps_over_self_time",
                "modeled_achieved_gbps", "measured_over_modeled",
                "modeled_bytes_total", "measured_over_modeled_bytes",
                "capture",
            ]))
            if s.get("error"):
                print(f"\nparse error: `{s['error']}`" + (
                    " (capture committed for offline re-parse)"
                    if s.get("capture") else " (no capture committed)"
                ))
            print()

    flightrec = by_stage.get("flightrec")
    if flightrec and flightrec["results"]:
        div = next(
            (r for r in reversed(flightrec["results"])
             if r.get("mode") in ("compare", "inject-fault")),
            None,
        )
        cost = next(
            (r for r in reversed(flightrec["results"])
             if "entries_costed" in r),
            None,
        )
        print("## Flight recorder (digest parity + compiled-cost "
              "ledger)\n")
        if div:
            print(md_table([
                {
                    "pair": p.get("pair"),
                    "result": (
                        p.get("skipped") and f"skipped: {p['skipped']}"
                        or ("fault@{} -> {}".format(
                            p.get("fault_tick"), p.get("located_tick"))
                            if "fault_located" in p else
                            ("DIVERGED @ t=" + str(p.get("tick"))
                             if p.get("diverged") else "clean"))
                    ),
                    "ticks_compared": p.get("compared"),
                }
                for p in div.get("pairs", [])
            ], ["pair", "result", "ticks_compared"]))
            print(f"\nbisector {'OK' if div.get('ok') else 'FAIL'} "
                  f"(mode: {div.get('mode')})\n")
        if cost:
            top = sorted(
                (e for e in cost.get("entries", []) if e.get("ok")),
                key=lambda e: -(e.get("flops") or 0),
            )[:8]
            print(f"compiled-cost ledger on {cost.get('platform')} "
                  f"({cost.get('entries_costed')} entries, "
                  f"{cost.get('total_compile_wall_s')}s total "
                  "compile):\n")
            print(md_table([
                {
                    "entry": e["entry"],
                    "flops": e.get("flops"),
                    "bytes_accessed": e.get("bytes_accessed"),
                    "jaxpr_eqns": e.get("jaxpr_eqns"),
                    "compile_s": e.get("compile_wall_s"),
                }
                for e in top
            ], ["entry", "flops", "bytes_accessed", "jaxpr_eqns",
                "compile_s"]))
            print()

    exch = by_stage.get("exchange")
    if exch and exch["results"]:
        legs = [r for r in exch["results"] if "exchange_mode" in r]
        if legs:
            print("## Frontier exchange: dense vs sparse delta "
                  "(host-mesh rehearsal, legs bitwise-checked)\n")
            print(md_table([
                {
                    "leg": f"{r.get('ring_mode')}/{r.get('exchange_mode')}",
                    "nodes": r.get("nodes"),
                    "topology": r.get("topology"),
                    "edge_cut_pct": r.get("edge_cut_pct"),
                    "modeled_dense_words_per_tick": (
                        (r.get("exchange") or {})
                        .get("modeled_dense_words_per_tick")
                    ),
                    "achieved_delta_words_per_tick": (
                        (r.get("exchange") or {})
                        .get("achieved_delta_words_per_tick")
                    ),
                    "delta_occupancy": (
                        (r.get("exchange") or {}).get("delta_occupancy")
                    ),
                    "wall_s": r.get("wall_s"),
                }
                for r in legs
            ], ["leg", "nodes", "topology", "edge_cut_pct",
                "modeled_dense_words_per_tick",
                "achieved_delta_words_per_tick", "delta_occupancy",
                "wall_s"]))
            dense = next((r for r in legs
                          if r.get("exchange_mode") == "dense"
                          and r.get("ring_mode") == "sharded"), None)
            delta = next((r for r in legs
                          if r.get("exchange_mode") == "delta"), None)
            d_ex = (delta or {}).get("exchange") or {}
            if dense is not None and d_ex.get(
                    "achieved_delta_words_per_tick"):
                ratio = (
                    d_ex.get("modeled_dense_words_per_tick", 0)
                    / d_ex["achieved_delta_words_per_tick"]
                )
                print(f"\ndense/delta wire ratio: {ratio:.2f}x "
                      "(achieved delta words/tick vs the dense "
                      "state-slice exchange on the same run)")
            print()

    hub = by_stage.get("exchange_hub")
    if hub and hub["results"]:
        legs = [r for r in hub["results"] if "exchange_mode" in r]
        if legs:
            print("## Degree-split hub/tail transport (host-mesh "
                  "rehearsal, legs bitwise-checked)\n")
            print(md_table([
                {
                    "leg": (
                        f"{r.get('ring_mode')}/{r.get('exchange_mode')}"
                        + (f"/K{r['async_k']}" if r.get("async_k") else "")
                    ),
                    "nodes": r.get("nodes"),
                    "topology": r.get("topology"),
                    "hub_count": (
                        (r.get("exchange") or {}).get("hub_count")
                    ),
                    "modeled_hub_words_per_tick": (
                        (r.get("exchange") or {})
                        .get("modeled_hub_words_per_tick")
                    ),
                    "achieved_words_per_tick": (
                        (r.get("exchange") or {})
                        .get("achieved_delta_words_per_tick")
                    ),
                    "wall_s": r.get("wall_s"),
                }
                for r in legs
            ], ["leg", "nodes", "topology", "hub_count",
                "modeled_hub_words_per_tick", "achieved_words_per_tick",
                "wall_s"]))
            hleg = next(
                (r for r in legs
                 if (r.get("exchange") or {}).get("mode") == "hub"
                 and not r.get("async_k")), None)
            h_ex = (hleg or {}).get("exchange") or {}
            if h_ex.get("achieved_delta_words_per_tick"):
                ratio = (
                    h_ex.get("modeled_dense_words_per_tick", 0)
                    / h_ex["achieved_delta_words_per_tick"]
                )
                print(f"\ndense/hub wire ratio: {ratio:.2f}x "
                      f"(hub_count {h_ex.get('hub_count')}, crossover_h "
                      f"{h_ex.get('crossover_h')}; achieved hub+tail "
                      "words/tick vs the dense state-slice exchange on "
                      "the same run)")
            if hub.get("pending_tpu"):
                print("\n(host-mesh CPU record — pending_tpu: re-captured "
                      "on the first window with a real multi-chip mesh)")
            print()

    csh = by_stage.get("campaign_sharded")
    if csh and csh["results"]:
        legs = [r for r in csh["results"] if "replica_shards" in r]
        if legs:
            print("## Campaigns × shards (factorized (replicas, nodes) "
                  "mesh, per-replica bitwise-checked)\n")
            print(md_table([
                {
                    "leg": f"{r.get('ring_mode')}/{r.get('exchange_mode')}",
                    "platform": r.get("platform"),
                    "nodes": r.get("nodes"),
                    "topology": r.get("topology"),
                    "mesh": (
                        f"{r.get('replica_shards')}x{r.get('node_shards')}"
                    ),
                    "bitwise": (
                        f"{r.get('bitwise_equal_replicas')}/"
                        f"{r.get('replicas')}"
                    ),
                    "campaign_warm_s/replica": r.get(
                        "campaign_warm_per_replica_s"),
                    "solo_warm_s/replica": r.get(
                        "solo_warm_per_replica_s"),
                    "speedup": r.get("speedup_warm_per_replica"),
                    "fresh_s": r.get("campaign_fresh_s"),
                }
                for r in legs
            ], ["leg", "platform", "nodes", "topology", "mesh", "bitwise",
                "campaign_warm_s/replica", "solo_warm_s/replica",
                "speedup", "fresh_s"]))
            if csh.get("pending_tpu"):
                print("\n(host-mesh CPU record — pending_tpu: re-captured "
                      "on the first window with a real multi-chip mesh)")
            print()

    asy = by_stage.get("async_ticks")
    if asy and asy["results"]:
        legs = [r for r in asy["results"] if "exchange_mode" in r]
        if legs:
            print("## Bounded-staleness async ticks (K-ahead frontiers, "
                  "host-mesh rehearsal; K=1 bitwise == sync, K>=2 "
                  "fixed-point-checked)\n")
            print(md_table([
                {
                    "leg": (
                        f"{r.get('ring_mode')}/{r.get('exchange_mode')}"
                        + (f"/K{r['async_k']}" if r.get("async_k") else "")
                    ),
                    "nodes": r.get("nodes"),
                    "topology": r.get("topology"),
                    "wall_s": r.get("wall_s"),
                    "wall_per_tick_s": r.get("wall_per_tick_s"),
                    "modeled_overlap_fraction": (
                        (r.get("exchange") or {})
                        .get("modeled_overlap_fraction")
                    ),
                }
                for r in legs
            ], ["leg", "nodes", "topology", "wall_s", "wall_per_tick_s",
                "modeled_overlap_fraction"]))
            sync = next(
                (r for r in legs
                 if r.get("ring_mode") == "sharded"
                 and not r.get("async_k")), None)
            best = min(
                (r for r in legs if (r.get("async_k") or 0) >= 2
                 and r.get("wall_per_tick_s")),
                key=lambda r: r["wall_per_tick_s"], default=None)
            if (sync and best and sync.get("wall_per_tick_s")
                    and best["wall_per_tick_s"]):
                ratio = sync["wall_per_tick_s"] / best["wall_per_tick_s"]
                print(f"\nsync/async wall-per-tick ratio: {ratio:.2f}x "
                      f"(best async leg K={best.get('async_k')} vs the "
                      "synchronous sharded exchange on the same run)")
            if asy.get("pending_tpu"):
                print("\n(host-mesh CPU record — pending_tpu: re-captured "
                      "on the first window with a real multi-chip mesh)")
            print()

    srv = by_stage.get("serve")
    if srv and srv["results"]:
        rows = [r for r in srv["results"] if r.get("bench") == "serve"]
        if rows:
            print("## Gossip-as-a-service (continuous-batching server, "
                  "every request bitwise-verified vs solo runs)\n")
            print(md_table(rows, [
                "platform", "requests", "signatures", "slots", "mesh",
                "batches", "requests_per_s", "p50_turnaround_s",
                "p99_turnaround_s", "slot_occupancy", "bitwise_ok",
            ]))
            if srv.get("pending_tpu"):
                print("\n(host-mesh CPU record — pending_tpu: re-captured "
                      "on the first window with a real multi-chip mesh)")
            print()

    for stage, title in (
        ("scale1m", "1M north star (ER p=0.001, 64-share staging plan)"),
        ("scale1m_ba", "1M scale-free (BA m=3)"),
        ("scale1m_full", "1M north star, full config (ER, 4096 shares)"),
    ):
        rec = by_stage.get(stage)
        if rec and rec["results"]:
            print(f"## {title}\n")
            print(md_table(rec["results"], [
                "metric", "value", "unit", "vs_baseline",
            ]))
            print()

    # Judge by each stage's LATEST record (matching the rendering above):
    # a failed-then-rerun-succeeded stage is a success, not a partial.
    failed = [s for s, r in by_stage.items() if not r.get("ok")]
    if failed:
        print(f"**Incomplete battery** — failed/aborted: {failed}. "
              f"Stage stderr tails are in `{os.path.basename(path)}`.")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
