#!/usr/bin/env bash
# Sanitizer leg for the native baseline (staticcheck's C++ counterpart):
# build native/gossip_native.cc with -Wall -Wextra -Werror and
# -fsanitize=address,undefined, then run the native parity suite
# (tests/test_native.py) against the instrumented library via the
# P2P_NATIVE_LIB override (runtime/native.py).
#
#   ./scripts/native_asan.sh
#
# Exit 0 iff the build is warning-free AND every test passes with no
# sanitizer report. The python interpreter itself is uninstrumented, so
# libasan is LD_PRELOADed; detect_leaks=0 because CPython intentionally
# leaks interned state at exit — the target is the .so's heap/UB
# discipline, not the interpreter's.
set -u
cd "$(dirname "$0")/.."

CXX=${CXX:-g++}
OUT="native/.libgossip_native.asan.so"

if ! make -C native asan CXX="$CXX" ASAN_OUT="$(basename "$OUT")"; then
  echo "native_asan: FAIL — build error or warning (-Werror)" >&2
  exit 1
fi

libasan=$("$CXX" -print-file-name=libasan.so)
if [ ! -e "$libasan" ]; then
  echo "native_asan: FAIL — libasan runtime not found ($libasan)" >&2
  exit 1
fi

# P2P_SANITIZER_RUN gates the two jnp-engine parity tests: jaxlib aborts
# when XLA compiles under a preloaded ASan runtime (not this repo's
# code). The pure-host partnered parity test keeps the C++ partnered
# paths exercised here; the jnp legs run in every regular tier-1 pass.
run_env=(
  "LD_PRELOAD=$libasan"
  "ASAN_OPTIONS=detect_leaks=0:abort_on_error=1"
  "UBSAN_OPTIONS=print_stacktrace=1:halt_on_error=1"
  "P2P_NATIVE_LIB=$PWD/$OUT"
  "P2P_SANITIZER_RUN=1"
  "JAX_PLATFORMS=cpu"
)

# Preflight: the suite must actually bind the INSTRUMENTED library — a
# load failure would fall back (or skip) and green-wash the leg.
if ! env "${run_env[@]}" python - <<'EOF'
import os, sys
sys.path.insert(0, os.getcwd())
from p2p_gossip_tpu.runtime import native
lib = native.load_library()
want = os.environ["P2P_NATIVE_LIB"]
assert lib is not None, "instrumented library failed to load"
assert getattr(lib, "_name", None) == want, (
    f"loaded {getattr(lib, '_name', None)!r}, wanted the instrumented "
    f"{want!r}"
)
print(f"native_asan: bound {want}", file=sys.stderr)
EOF
then
  echo "native_asan: FAIL — instrumented library did not bind" >&2
  rm -f "$OUT"
  exit 1
fi

env "${run_env[@]}" python -m pytest tests/test_native.py -q \
  -p no:cacheprovider
rc=$?
rm -f "$OUT"
if [ $rc -ne 0 ]; then
  echo "native_asan: FAIL — test or sanitizer report (rc=$rc)" >&2
else
  echo "native_asan: OK — warning-free build, suite green under" \
       "ASan+UBSan" >&2
fi
exit $rc
