"""Million-node scale demonstration — the BASELINE.json north-star config.

Target (BASELINE.json): "1M-node p=0.001 gossip to 99% share coverage on
v5e-8 < 60 s". This script runs that workload on a SINGLE chip: a 1M-node
Erdős–Rényi p=0.001 graph (~500M undirected links, mean degree ~1000), 4096
shares flooded from random origins at t=0, per-share time-to-99%-coverage
reported — the reference's NS-3 event loop (p2pnetwork.cc:193) processes
~10-100K events/s and would need ~degree × N × shares ≈ 4×10^12 events for
the same experiment.

Usage: python scripts/scale_1m.py [--nodes 1000000] [--shares 4096]
       [--cache /tmp/er1m.npz]

Prints one JSON line on stdout (same shape as bench.py); diagnostics on
stderr. The graph build is the slow host-side step (~3.5 min native C++ at
1M); pass --cache to reuse it across runs.
"""

import argparse
import json
import os
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=1_000_000)
    ap.add_argument("--prob", type=float, default=0.001)
    ap.add_argument("--shares", type=int, default=4096)
    ap.add_argument("--horizon", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--cache", type=str, default="",
        help="npz path to cache the built graph across runs",
    )
    args = ap.parse_args()

    import jax

    import p2p_gossip_tpu as pg
    from p2p_gossip_tpu.models.topology import Graph
    from p2p_gossip_tpu.engine.sync import (
        DeviceGraph, run_flood_coverage, time_to_coverage,
    )
    from p2p_gossip_tpu.runtime import native

    t0 = time.perf_counter()
    if args.cache and os.path.exists(args.cache):
        d = np.load(args.cache)
        graph = Graph(n=int(d["n"]), indptr=d["indptr"], indices=d["indices"])
        log(f"graph loaded from {args.cache}: {time.perf_counter()-t0:.1f}s")
    else:
        graph = native.native_erdos_renyi(args.nodes, args.prob, seed=args.seed)
        if graph is None:
            graph = pg.erdos_renyi(args.nodes, args.prob, seed=args.seed)
        log(f"graph built: {time.perf_counter()-t0:.1f}s")
        if args.cache:
            np.savez(args.cache, n=graph.n, indptr=graph.indptr,
                     indices=graph.indices)
    log(
        f"N={graph.n} edges={graph.num_edges} dmax={graph.max_degree} "
        f"devices={jax.devices()}"
    )

    t0 = time.perf_counter()
    dg = DeviceGraph.build(graph)
    log(f"device staging: {time.perf_counter()-t0:.1f}s")

    rng = np.random.default_rng(args.seed)
    origins = rng.integers(0, graph.n, args.shares).astype(np.int32)

    t0 = time.perf_counter()
    stats, cov = run_flood_coverage(
        graph, origins, args.horizon, device_graph=dg
    )
    warm_wall = time.perf_counter() - t0
    log(f"warmup (incl. compile): {warm_wall:.1f}s")

    t0 = time.perf_counter()
    stats, cov = run_flood_coverage(
        graph, origins, args.horizon, device_graph=dg
    )
    wall = time.perf_counter() - t0

    ttc = time_to_coverage(cov, graph.n, 0.99)
    processed = stats.totals()["processed"]
    full = processed == args.shares * graph.n
    log(
        f"flood: {processed} node-updates in {wall:.1f}s, full coverage: "
        f"{full}, ttc99 median {int(np.median(ttc))} / max {int(ttc.max())} "
        f"ticks"
    )
    print(
        json.dumps(
            {
                "metric": f"wall seconds to 99% coverage, {args.shares} "
                f"shares on a {graph.n}-node p={args.prob:g} graph "
                "(single chip)",
                "value": round(wall, 2),
                "unit": "s",
                "vs_baseline": round(60.0 / wall, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
