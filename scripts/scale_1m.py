"""Million-node scale demonstration — the BASELINE.json north-star config.

Target (BASELINE.json): "1M-node p=0.001 gossip to 99% share coverage on
v5e-8 < 60 s". This script runs that workload on a SINGLE chip: a 1M-node
Erdős–Rényi p=0.001 graph (~500M undirected links, mean degree ~1000), 4096
shares flooded from random origins at t=0, per-share time-to-99%-coverage
reported — the reference's NS-3 event loop (p2pnetwork.cc:193) processes
~10-100K events/s and would need ~degree × N × shares ≈ 4×10^12 events for
the same experiment.

Usage: python scripts/scale_1m.py [--nodes 1000000] [--shares 4096]
       [--cache /tmp/er1m.npz]

Prints one JSON line on stdout (same shape as bench.py); diagnostics on
stderr. The graph build is the slow host-side step (~3.5 min native C++ at
1M); pass --cache to reuse it across runs.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

# Self-locate the repo so the script runs from any cwd. Deliberately an
# in-process sys.path edit and NOT a PYTHONPATH requirement: PYTHONPATH
# propagates into the TPU tunnel plugin's helper subprocess and breaks its
# backend registration ("Backend 'axon' is not in the list of known
# backends" whenever PYTHONPATH points here).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=1_000_000)
    ap.add_argument("--prob", type=float, default=0.001)
    ap.add_argument("--shares", type=int, default=4096)
    ap.add_argument(
        "--chunk", type=int, default=0,
        help="Shares per device pass (0 = auto). Auto sizes the chunk from "
        "the resident-HBM model (engine.sync.flood_resident_hbm_bytes) "
        "against P2P_HBM_BUDGET_GB (default 10 on TPU, unlimited "
        "elsewhere): the full 4096-share pass at 1M nodes models ~12.6 GB "
        "and crashed the 16 GB v5e worker (2026-07-31); 2048-share "
        "passes model ~8.8 GB. Chunks below 4096 shares underfill the "
        "TPU's 128-lane tile (slower gather per byte), so auto halves as "
        "little as possible.",
    )
    ap.add_argument(
        "--block", type=int, default=8,
        help="Degree-block for the gather-OR scan. The per-step gather "
        "intermediate is rows x block x words x 4 B — at N=1M / 4096 "
        "shares the 100K-swept block of 64 wants ~26 GB of HBM, so the "
        "default here stays at 8 (~4 GB).",
    )
    ap.add_argument("--horizon", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--cache", type=str, default="",
        help="npz path to cache the built graph across runs",
    )
    ap.add_argument(
        "--topology", choices=("er", "ba"), default="er",
        help="er = the north-star ER config; ba = BASELINE config 4's "
        "Barabasi-Albert scale-free topology (--baM edges per node)",
    )
    ap.add_argument("--baM", type=int, default=3)
    from p2p_gossip_tpu.utils.platform import add_cpu_arg, apply_cpu_arg

    add_cpu_arg(ap)
    ap.add_argument(
        "--mesh", type=str, default="",
        help="SxN (share-shards x node-shards): run the shard_map sharded "
        "engine over a device mesh instead of the single-device engine — "
        "the BASELINE v5e-8 configuration when 8 chips are attached",
    )
    args = ap.parse_args()
    apply_cpu_arg(args)

    import jax

    import p2p_gossip_tpu as pg
    from p2p_gossip_tpu.engine.sync import (
        DeviceGraph, run_flood_coverage, time_to_coverage,
    )
    from p2p_gossip_tpu.runtime import native

    # A wedged TPU tunnel hangs in-process backend init; wait it out with
    # killable subprocess probes (shared with bench.py). Unlike bench.py
    # this script has no CPU fallback — a 1M-node run is TPU-or-nothing —
    # so use the long-wait budget (bound it per-run with
    # P2P_LONG_DEVICE_WAIT_S; P2P_DEVICE_WAIT_S can only raise it).
    from p2p_gossip_tpu.utils.platform import long_device_wait_s, wait_for_device

    wait_for_device(max_wait_s=long_device_wait_s())

    # Initialize the TPU backend BEFORE the multi-GB graph load: the axon
    # tunnel plugin fails to register under the memory pressure / delay of
    # loading first (observed: "Backend 'axon' is not in the list of known
    # backends" iff devices() first fires after the 4 GB npz load).
    devices = jax.devices()

    # Cache handling: reusing a graph built for different flags would
    # attribute the benchmark to the wrong topology (same protection the
    # CLI's --graphFile has). The load/validate/build/save protocol and
    # fingerprint are shared with mesh_rehearsal.py via
    # load_or_build_graph_cache so the two scripts' caches interoperate.
    from p2p_gossip_tpu.models.topology import load_or_build_graph_cache

    def build():
        t0 = time.perf_counter()
        if args.topology == "ba":
            graph = native.native_barabasi_albert(
                args.nodes, m=args.baM, seed=args.seed
            )
            if graph is None:
                graph = pg.barabasi_albert(
                    args.nodes, m=args.baM, seed=args.seed
                )
            log(f"BA graph built: {time.perf_counter()-t0:.1f}s")
        else:
            graph = native.native_erdos_renyi(
                args.nodes, args.prob, seed=args.seed
            )
            if graph is None:
                graph = pg.erdos_renyi(args.nodes, args.prob, seed=args.seed)
            log(f"graph built: {time.perf_counter()-t0:.1f}s")
        return graph

    graph = load_or_build_graph_cache(
        args.cache, topology=args.topology, nodes=args.nodes, prob=args.prob,
        ba_m=args.baM, seed=args.seed, build=build, log=log,
    )
    log(
        f"N={graph.n} edges={graph.num_edges} dmax={graph.max_degree} "
        f"devices={devices}"
    )

    mesh = None
    if args.mesh:
        from p2p_gossip_tpu.parallel.mesh import make_mesh

        shares_shards, node_shards = (int(x) for x in args.mesh.split("x"))
        mesh = make_mesh(node_shards, shares_shards)
        log(f"mesh: {shares_shards} share-shards x {node_shards} node-shards")
        dg = None
    else:
        t0 = time.perf_counter()
        dg = DeviceGraph.build(graph)
        log(f"device staging: {time.perf_counter()-t0:.1f}s")

    rng = np.random.default_rng(args.seed)
    origins = rng.integers(0, graph.n, args.shares).astype(np.int32)
    # pad: the explicit chunk_size handed to run_flood_coverage (None =
    # the engine's default MIN_CHUNK_SHARES lane pad); chunk: the origin
    # slice per pass. pad may exceed chunk (a 64-share pass padded to the
    # widest W that fits the budget).
    if args.chunk:
        chunk = max(32, min(args.chunk, args.shares))
        pad = chunk
        if mesh is not None:
            log(
                f"mesh: explicit --chunk forwards chunk_size={pad} to the "
                "sharded engine (per-pass resident relief, not just origin "
                "slicing)"
            )
    else:
        # Auto: fit the resident-HBM model into the device budget. Only
        # the single-chip TPU path is budgeted by default — the host has
        # RAM to spare and the mesh path divides rows across chips. None
        # = the default pad already fits (or budgeting is off): stage
        # exactly what the engine always staged.
        from p2p_gossip_tpu.engine.sync import (
            MIN_CHUNK_SHARES, auto_chunk_shares, flood_resident_hbm_bytes,
        )
        from p2p_gossip_tpu.ops.bitmask import num_words

        on_tpu = devices[0].platform == "tpu" and mesh is None
        # Mesh mode ignores the budget entirely (even an exported
        # P2P_HBM_BUDGET_GB): the sharded engine pads every pass to its
        # own chunk default, so a pad computed here would slice origins
        # and log a staged shape that never actually changes — per-chip
        # relief on the mesh comes from the node axis, not share width.
        budget = 0.0 if mesh is not None else float(
            os.environ.get("P2P_HBM_BUDGET_GB", "10" if on_tpu else "0")
        ) * 1e9
        pad = auto_chunk_shares(graph.degree, args.shares, args.block, budget)
        chunk = args.shares if pad is None else min(pad, args.shares)
        if pad is not None:
            default_w = num_words(max(args.shares, MIN_CHUNK_SHARES))
            pad_model = flood_resident_hbm_bytes(
                graph.degree, num_words(pad), args.block
            )
            log(
                f"auto-chunk: default pad models "
                f"{flood_resident_hbm_bytes(graph.degree, default_w, args.block) / 1e9:.1f} GB "
                f"resident > {budget / 1e9:.1f} GB budget; padding to "
                f"{pad} shares ({pad_model / 1e9:.1f} GB)"
                + (f", {chunk} origins per pass" if chunk < args.shares else "")
            )
            if pad_model > budget:
                # auto_chunk_shares floored at min_chunk without meeting
                # the budget (it warns too); say so here in the staging
                # log, or the plan above reads as budget-approved.
                log(
                    f"WARNING auto-chunk budget NOT satisfied: pad {pad} "
                    f"still models {pad_model / 1e9:.1f} GB "
                    f"> {budget / 1e9:.1f} GB (fixed ELL terms dominate); "
                    "proceeding with the least-bad staging."
                )

    def flood_all():
        """Shares are independent: chunked passes, counters additive."""
        processed = 0
        covs = []
        for lo in range(0, args.shares, chunk):
            if mesh is not None:
                from p2p_gossip_tpu.parallel.engine_sharded import (
                    run_sharded_flood_coverage,
                )

                stats, cov = run_sharded_flood_coverage(
                    graph, origins[lo : lo + chunk], args.horizon, mesh,
                    block=args.block,
                    # An explicit --chunk promises resident-footprint
                    # relief on the mesh path too (as mesh_rehearsal.py
                    # does): without forwarding it, each sliced pass is
                    # re-padded to the sharded engine's 4096-share
                    # default — extra passes, no memory relief
                    # (round-4 advisor finding).
                    **({"chunk_size": pad} if args.chunk else {}),
                )
            else:
                stats, cov = run_flood_coverage(
                    graph, origins[lo : lo + chunk], args.horizon,
                    device_graph=dg, block=args.block, chunk_size=pad,
                )
            processed += stats.totals()["processed"]
            covs.append(cov)
        return processed, np.concatenate(covs, axis=1)

    t0 = time.perf_counter()
    flood_all()
    warm_wall = time.perf_counter() - t0
    log(f"warmup (incl. compile): {warm_wall:.1f}s")

    t0 = time.perf_counter()
    processed, cov = flood_all()
    wall = time.perf_counter() - t0

    ttc = time_to_coverage(cov, graph.n, 0.99)
    full = processed == args.shares * graph.n
    log(
        f"flood: {processed} node-updates in {wall:.1f}s, full coverage: "
        f"{full}, ttc99 median {int(np.median(ttc))} / max {int(ttc.max())} "
        f"ticks"
    )
    print(
        json.dumps(
            {
                "metric": f"wall seconds to 99% coverage, {args.shares} "
                f"shares on a {graph.n}-node "
                + (
                    f"BA(m={args.baM}) graph"
                    if args.topology == "ba"
                    else f"p={args.prob:g} graph"
                )
                + (
                    f" ({args.mesh} mesh)" if args.mesh else " (single chip)"
                )
                + (
                    ""
                    if devices[0].platform == "tpu"
                    else f" [{devices[0].platform}]"
                ),
                "value": round(wall, 2),
                "unit": "s",
                "vs_baseline": round(60.0 / wall, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
