#!/usr/bin/env bash
# Tier-1 gate as one command — the EXACT verify line from ROADMAP.md,
# preceded by the static-analysis gate (scripts/staticcheck.py: jaxpr
# invariant audit + recompile sentinel + AST lint over every registered
# entry point) and a pre-flight check that the `slow` marker is
# registered (an unregistered marker makes `-m 'not slow'` silently
# rely on pytest's default-warn behavior; registration lives in
# pyproject.toml).
#
#   ./scripts/ci_tier1.sh
#
# Exit code is pytest's (or 1 if staticcheck finds a violation).
# DOTS_PASSED echoes the passed-dot count the driver greps for.
set -u
cd "$(dirname "$0")/.."

# Static-analysis gate first: cheap (~10 s on CPU), and a dirty tree
# should fail before the 6-minute pytest pass, not after.
if ! JAX_PLATFORMS=cpu python scripts/staticcheck.py --json; then
  echo "ci_tier1: FAIL — staticcheck violations (run" \
       "'python scripts/staticcheck.py' for the human report)" >&2
  exit 1
fi

# Telemetry smoke: a tiny flood through the real CLI with --telemetry,
# its JSONL stream schema-validated and its ring metrics reconciled
# against the run's counters (scripts/run_report.py --capture-smoke).
# Cheap (~10 s) and catches a broken emit path before the long pytest
# pass; the staticcheck gate above already proved telemetry-OFF runs
# trace the uninstrumented kernels.
if ! JAX_PLATFORMS=cpu python scripts/run_report.py --capture-smoke \
    > /tmp/_t1_telemetry.json; then
  echo "ci_tier1: FAIL — telemetry smoke (see /tmp/_t1_telemetry.json;" \
       "run 'python scripts/run_report.py --capture-smoke' to reproduce)" >&2
  exit 1
fi

# Flight-recorder smoke: the smallest engine pair (native event engine
# vs the compiled sync kernel) on a tiny seeded workload must agree
# digest-for-digest (clean bisection), and the bisector's fault
# injection must name the injected tick exactly — a bisector blind to
# divergence would otherwise stay green forever (scripts/divergence.py).
if ! JAX_PLATFORMS=cpu python scripts/divergence.py --pair native-sync \
    --n 64 --shares 3 --horizon 16 --json > /tmp/_t1_divergence.json; then
  echo "ci_tier1: FAIL — divergence smoke (see /tmp/_t1_divergence.json;" \
       "run 'python scripts/divergence.py --pair native-sync' to" \
       "reproduce)" >&2
  exit 1
fi
if ! JAX_PLATFORMS=cpu python scripts/divergence.py --pair native-sync \
    --n 64 --shares 3 --horizon 16 --inject-fault 4 --json \
    > /tmp/_t1_divergence_fault.json; then
  echo "ci_tier1: FAIL — divergence fault-injection self-test (see" \
       "/tmp/_t1_divergence_fault.json)" >&2
  exit 1
fi

# Async smoke: the bounded-staleness runner (exchange="async", K=2) must
# stay digest-identical to the synchronous run on the clamped delay
# line, and the bisector must still name an injected fault on that pair
# (the pair shards a 2x2 mesh — XLA_FLAGS forces 8 virtual CPU devices,
# matching tests/conftest.py).
if ! JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python scripts/divergence.py --pair sync-async \
    --n 64 --shares 3 --horizon 16 --json > /tmp/_t1_async.json; then
  echo "ci_tier1: FAIL — async digest smoke (see /tmp/_t1_async.json;" \
       "run 'python scripts/divergence.py --pair sync-async' to" \
       "reproduce)" >&2
  exit 1
fi
if ! JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python scripts/divergence.py --pair sync-async \
    --n 64 --shares 3 --horizon 16 --inject-fault 4 --json \
    > /tmp/_t1_async_fault.json; then
  echo "ci_tier1: FAIL — async fault-injection self-test (see" \
       "/tmp/_t1_async_fault.json)" >&2
  exit 1
fi

# Hub smoke: the degree-split hub/tail transport (exchange="hub", a
# forced 8-row hub set — the tiny ER graph has no natural hubs) must
# stay digest-identical to the dense exchange, and the bisector must
# still name an injected fault on that pair.
if ! JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python scripts/divergence.py --pair sync-hub \
    --n 64 --shares 3 --horizon 16 --json > /tmp/_t1_hub.json; then
  echo "ci_tier1: FAIL — hub digest smoke (see /tmp/_t1_hub.json;" \
       "run 'python scripts/divergence.py --pair sync-hub' to" \
       "reproduce)" >&2
  exit 1
fi
if ! JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python scripts/divergence.py --pair sync-hub \
    --n 64 --shares 3 --horizon 16 --inject-fault 4 --json \
    > /tmp/_t1_hub_fault.json; then
  echo "ci_tier1: FAIL — hub fault-injection self-test (see" \
       "/tmp/_t1_hub_fault.json)" >&2
  exit 1
fi

# Server smoke: a mixed request trace (12 requests, 2 topologies x 3
# protocols x mixed replica counts) drained in-process through the
# continuous-batching server on an 8-virtual-device slot mesh, each
# request bitwise-compared against a solo batch/campaign run with the
# same seeds (scripts/serve_bench.py exits non-zero on any mismatch or
# non-done request).
if ! JAX_PLATFORMS=cpu python scripts/serve_bench.py --smoke \
    > /tmp/_t1_serve.json; then
  echo "ci_tier1: FAIL — server smoke (see /tmp/_t1_serve.json; run" \
       "'python scripts/serve_bench.py --smoke' to reproduce)" >&2
  exit 1
fi

# Marker registration check: `pytest --markers` must list `slow`.
if ! JAX_PLATFORMS=cpu python -m pytest --markers -p no:cacheprovider 2>/dev/null \
    | grep -q "^@pytest.mark.slow:"; then
  echo "ci_tier1: FAIL — 'slow' marker is not registered (pyproject.toml" \
       "[tool.pytest.ini_options] markers)" >&2
  exit 1
fi

# The tier-1 verify line, verbatim from ROADMAP.md.
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
