"""One-shot on-chip evidence battery (VERDICT round-2 item #1).

Two rounds of on-chip evidence have been lost to TPU-tunnel downtime: the
tunnel answers rarely, a worker crash wedges it for ~1h+, and each manual
run pays its own device wait and can kill the window for the next. This
script converts ONE tunnel-up window into every artifact the judge needs,
value-first within safety bands, persisting each stage's results the moment the
stage completes — a crash in stage k cannot cost stages 1..k-1.

Stages (value-first within safety bands — see the note after the list):

  bench     — bench.py on the real chip      -> the headline BENCH JSON
  protocols — protocol_compare.py at 100K    -> flood/pushpull/pull/pushk table
              (standard XLA engines, low risk — before any Pallas runs)
  kernel    — kernel_bench.py at 100K rows   -> Pallas-vs-XLA A/B table
  bench_rep2 — bench.py again                -> headline variance estimate:
  bench_rep3 — bench.py again                   three records distinguish
               drift from noise (round-1 5.60e8 vs round-4 4.41e8 was
               undecidable from singles); cheap (~90 s each) and safe.
  campaign  — sweep.py over the acceptance campaign spec (all four
               protocols at R=32 x N=1024, --compare-sequential) -> the
               first HARDWARE record of the vmapped campaign kernels
               (flood + the batched Demers trio) and their measured
               speedup vs sequential-per-seed; standard XLA (vmap of the
               already-validated engines), so it sits in the safe band
               before any 1M or Pallas stage. Today's campaign numbers
               are CPU-only (docs/artifacts/campaign_accept_cpu.jsonl,
               protocol_campaign_accept_cpu.jsonl).
  staticcheck — staticcheck.py --json --compile -> the static-analysis
               gate's on-chip leg: the jaxpr audit + recompile sentinel
               run as on CPU, and every registered entry point is
               additionally lowered + compiled on the real chip (an
               entry that audits clean can still fail Mosaic/XLA on
               hardware shapes). Standard XLA compiles only — no
               execution at scale — so it sits in the safe band after
               campaign and before any 1M stage.
  telemetry — run_report.py --capture-smoke at a modest on-chip shape:
               a flood with the in-jit metric rings ON, its JSONL stream
               schema-validated and the per-tick ring metrics reconciled
               against the run's final counters — the first hardware
               execution of the instrumented kernels (today's telemetry
               evidence is CPU-only, docs/RESULTS.md). Standard XLA, tiny
               extra carry — safe band, right after staticcheck compiled
               the same instrumented entries.
  flightrec — divergence.py --json --with-cost -> the flight recorder's
               hardware leg: every engine pair re-run with per-tick
               state digests ON and the streams bisected (clean chip
               runs must report zero divergence — the first cross-engine
               bitwise-parity evidence on real hardware), plus the
               compiled-cost ledger (XLA cost_analysis flops/bytes +
               compile wall time) for the engine.sync entries on the
               chip's compiler. Tiny sims + standard XLA — safe band,
               right after telemetry validated the same instrumented
               kernels.
  campaign_sharded — mesh_rehearsal.py --replicas 4 at the acceptance
               shape (100K BA, (2 replicas x 4 nodes) split, dense +
               delta legs): the factorized campaigns-x-shards program
               with per-replica bitwise checks and warm/fresh walls vs
               the sequential solo-sharded loop. Host-mesh CPU by
               design (like exchange); records carry pending_tpu until
               a real multi-chip mesh is attached.
  async_ticks — mesh_rehearsal.py --async-k 1,2,4 at the acceptance
               shape (100K BA, 8-way node shard, dense + delta
               transports): the bounded-staleness async read path next
               to its synchronous twins — K=1 bitwise-equal, K>=2
               fixed-point-equal (the parity ladder asserts inside the
               script), warm wall per tick and modeled overlap fraction
               per leg in the rows. Host-mesh CPU by design (like
               exchange); records carry pending_tpu until a real
               multi-chip mesh is attached.
  serve     — serve_bench.py at the acceptance trace (100 mixed
               requests, 2 topologies x 3 protocols x mixed replica
               counts, every request bitwise-verified against a solo
               campaign run): requests/s, p50/p99 turnaround and slot
               occupancy for the continuous-batching server. Runs on
               the 8-virtual-device host slot mesh by design (the slot
               mesh wants >= 4 devices; the tunnel attaches one chip) —
               records carry pending_tpu until a real multi-chip mesh
               is attached, like the other host-mesh stages.
  scale1m   — scale_1m.py --shares 64 --chunk 64 -> the 1M ER on-chip
               line at the minimal resident footprint (pad W=2, ~5.2 GB
               modeled = essentially the bare ELL). The full-config
               attempt lives in scale1m_full, ordered behind every
               proven-safe stage, because its W=128 one-pass shape
               crashed the TPU worker on 2026-07-31 (window #3) and a
               crash wedges the tunnel for every stage after it.
  scale1m_ba — scale_1m.py --topology ba     -> BASELINE config 4 (1M
               scale-free) JSON line
  sweep250  — kernel_bench.py --rows 250000  -> coverage A/B at 250K
               (already survived on-chip in window #2) plus the gather
               block-128 / word-width / RCM rows — real tuning value.
  profile   — profile_capture.py             -> profiled bench pass +
               parsed XPlane trace: MEASURED HBM bytes vs the modeled
               roofline (round-4 verdict item #4). Cheap (~one bench),
               but jax.profiler through the tunnel is unvalidated, so
               it sits after the proven-safe stages and before the one
               stage that has actually crashed the worker.
  scale1m_full — scale_1m.py at the full default config (ER 1M, 4096
               shares). Dead last on purpose: this invocation crashed
               the TPU worker in window #3 (battery_latest.jsonl stage
               scale1m, rc=1, JaxRuntimeError "TPU worker process
               crashed", after graph build + staging succeeded — the
               resident-HBM model puts the one-pass W=128 footprint at
               ~12.6 GB on a 16 GB chip; Pallas is gated off at 1M, so
               it is not implicated). scale_1m.py now auto-chunks
               against P2P_HBM_BUDGET_GB (4096 shares -> 2x 2048-share
               passes, ~8.8 GB modeled), which should make it
               survivable; it still runs after every proven-safe stage.

  (The round-4 sweep500/sweep1m stages — the Pallas coverage kernel at
  500K/1M rows — are deleted: the bake-off measured the kernel LOSING
  above its 100K crossover and production gates it off there
  (ops/pallas_kernels.py), so those rows would characterize a path
  nothing runs, at real worker-crash risk, in tunnel windows the 1M
  ladder and the roofline rows need. Round-4 verdict weak item #4.)

Observed tunnel windows are ~50 min; the order above is value-first
within safety bands so a short window always banks the most important
never-captured artifact next.

Between stages a short health probe checks the tunnel still answers; a
failed probe aborts the battery (later stages would only burn the wedge
clock) and records why. Each stage runs in its own subprocess with its
own wall budget, with PYTHONPATH stripped (it breaks the axon plugin's
helper subprocess — see scripts/scale_1m.py header).

Artifacts: one JSONL record per stage appended to
docs/artifacts/battery_<UTC>.jsonl as each stage finishes (plus a
'battery_latest.jsonl' copy), and a one-line summary JSON on stdout.

Usage:
  python scripts/onchip_battery.py                 # full battery
  python scripts/onchip_battery.py --stages bench,kernel
  python scripts/onchip_battery.py --smoke         # tiny CPU shapes, CI
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import time
from datetime import datetime, timezone

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(REPO, "scripts")
ART_DIR = os.path.join(REPO, "docs", "artifacts")

STAGE_ORDER = (
    "bench", "protocols", "kernel", "bench_rep2", "bench_rep3",
    "campaign", "staticcheck", "telemetry", "flightrec", "exchange",
    "exchange_hub", "campaign_sharded", "async_ticks", "serve",
    "scale1m", "scale1m_ba", "sweep250", "profile", "scale1m_full",
)

# Host-mesh stages: mesh_rehearsal.py pins JAX_PLATFORMS=cpu by design
# (the delta exchange and the factorized campaign mesh need >= 4 devices;
# the tunnel attaches one chip), so their records are CPU mechanics
# evidence, not chip numbers. Each record is stamped ``pending_tpu``
# until a run happens with a real multi-chip TPU mesh attached —
# --skip-done stops counting a pending record as done the moment the
# probe sees such a mesh, so the first multi-chip window re-runs these
# rows on hardware (ROADMAP: PR 11 exchange follow-up).
PENDING_TPU_STAGES = (
    "exchange", "exchange_hub", "campaign_sharded", "async_ticks", "serve",
)


def log(msg: str) -> None:
    print(f"[battery] {msg}", file=sys.stderr, flush=True)


def stage_env(extra: dict | None = None) -> dict:
    """Subprocess env for a stage: platform.tunnel_safe_env (repo entries
    filtered from PYTHONPATH — the rationale lives there) plus
    stage-specific overrides."""
    from p2p_gossip_tpu.utils.platform import tunnel_safe_env

    return tunnel_safe_env(extra)


def tunnel_healthy(probe_timeout_s: float = 150.0) -> bool:
    """THE device probe (platform.run_device_probe — the same definition
    wait_for_device retries), so the battery's abort decisions can't
    drift from what the stages themselves wait for."""
    from p2p_gossip_tpu.utils.platform import run_device_probe

    ok, err = run_device_probe(probe_timeout_s, env=stage_env())
    if not ok:
        log(f"health probe failed: {err}")
    return ok


def multichip_attached(probe_timeout_s: float = 150.0) -> bool:
    """True iff the attached device set is a real multi-chip TPU mesh
    (>= 4 chips) — the signal that the host-mesh stages' pending_tpu
    records are finally upgradable to hardware evidence. Killable
    subprocess for the same wedged-tunnel reason as tunnel_healthy;
    any failure reads as "no mesh" (the conservative answer: pending
    records keep counting as done and no window is burned re-running
    CPU stages). Memoized — the skip-done scan and the per-record
    stamping both ask, and one probe per battery run is enough."""
    global _MULTICHIP
    if _MULTICHIP is None:
        snippet = (
            "import jax; d = jax.devices(); print(d[0].platform, len(d))"
        )
        try:
            out = subprocess.run(
                [sys.executable, "-c", snippet], check=True,
                timeout=probe_timeout_s, capture_output=True, text=True,
                env=stage_env(),
            ).stdout.split()
            _MULTICHIP = out[0] == "tpu" and int(out[1]) >= 4
        except Exception:
            _MULTICHIP = False
    return _MULTICHIP


_MULTICHIP: bool | None = None


def stage_specs(args) -> dict:
    """argv + env + budget per stage. Smoke mode swaps in tiny CPU shapes
    so the battery's own machinery is testable without a chip."""
    py = sys.executable
    if args.smoke:
        # All smoke stages pin CPU: wait_for_device no-ops there, so the
        # battery machinery is exercised with zero tunnel dependency.
        cpu = {"JAX_PLATFORMS": "cpu"}
        kb_small = [
            py, os.path.join(SCRIPTS, "kernel_bench.py"),
            "--rows", "2000", "--words", "8", "--iters", "3",
        ]
        def bench_spec():
            return {
                "argv": [py, os.path.join(REPO, "bench.py")],
                "env": {**cpu, "P2P_BENCH_SMOKE": "1"},
                "budget": args.stage_budget or 900,
            }

        return {
            # One spec for the headline bench and its variance repeats —
            # a drifted copy would make the repeats measure a different
            # configuration than the headline.
            **{n: bench_spec() for n in ("bench", "bench_rep2", "bench_rep3")},
            "protocols": {
                "argv": [
                    py, os.path.join(SCRIPTS, "protocol_compare.py"),
                    "--nodes", "400", "--prob", "0.03", "--shares", "8",
                    "--horizon", "32", "--json",
                ],
                "env": cpu,
                "budget": args.stage_budget or 600,
            },
            "kernel": {
                "argv": kb_small,
                "env": cpu,
                "budget": args.stage_budget or 600,
            },
            # Smoke sweeps stay tiny: the point is the battery's
            # per-process isolation machinery, not the row counts.
            "sweep250": {
                "argv": kb_small + ["--skip-gather"],
                "env": cpu,
                "budget": args.stage_budget or 600,
            },
            "campaign": {
                # The built-in example spec (2 protocols x 2 loss rates x
                # 8 seeds at 256 nodes): exercises the vmapped campaign
                # path end to end, including one sequential comparison
                # per protocol, at CPU-smoke scale.
                "argv": [
                    py, os.path.join(SCRIPTS, "sweep.py"),
                    "--example", "--compare-sequential", "--no-report",
                ],
                "env": cpu,
                "budget": args.stage_budget or 900,
            },
            "profile": {
                # --art-dir follows the battery's own artifact dir so a
                # smoke fire never drops a CPU capture into
                # docs/artifacts as if it were chip evidence.
                "argv": [
                    py, os.path.join(SCRIPTS, "profile_capture.py"),
                    "--smoke", "--art-dir", args.art_dir,
                ],
                "env": cpu,
                "budget": args.stage_budget or 900,
            },
            "staticcheck": {
                # Full gate incl. the --compile leg, on host CPU: the
                # smoke run proves the stage machinery and record shape.
                "argv": [
                    py, os.path.join(SCRIPTS, "staticcheck.py"),
                    "--json", "--compile",
                ],
                "env": cpu,
                "budget": args.stage_budget or 900,
            },
            "telemetry": {
                # Same pipeline as the ci_tier1 smoke, pinned to CPU.
                "argv": [
                    py, os.path.join(SCRIPTS, "run_report.py"),
                    "--capture-smoke",
                ],
                "env": cpu,
                "budget": args.stage_budget or 900,
            },
            "flightrec": {
                # Digest parity across engine pairs + the cost ledger for
                # one kernel, at smoke shapes — proves the stage record
                # shape battery_report.py renders.
                "argv": [
                    py, os.path.join(SCRIPTS, "divergence.py"), "--json",
                    "--n", "64", "--shares", "3", "--horizon", "16",
                    "--with-cost", "engine.sync._run_chunk_while",
                ],
                "env": cpu,
                "budget": args.stage_budget or 900,
            },
            "campaign_sharded": {
                # Factorized (replicas x nodes) campaign at smoke
                # shapes: 4 replicas on a (2, 4) virtual mesh, dense +
                # delta legs, every replica bitwise-checked against its
                # solo sharded run inside the script.
                "argv": [
                    py, os.path.join(SCRIPTS, "mesh_rehearsal.py"),
                    "--nodes", "2000", "--prob", "0.01", "--shares", "16",
                    "--horizon", "24", "--replicas", "4",
                    "--replica-shards", "2", "--exchange", "ab",
                ],
                "env": cpu,
                "budget": args.stage_budget or 900,
            },
            "async_ticks": {
                # Bounded-staleness async legs at smoke shapes: sync,
                # K=1 (bitwise anchor), and K=2 (fixed-point check)
                # dense legs side by side, parity asserted inside the
                # script before any timing lands in a row.
                "argv": [
                    py, os.path.join(SCRIPTS, "mesh_rehearsal.py"),
                    "--nodes", "2000", "--prob", "0.01", "--shares", "16",
                    "--horizon", "24", "--async-k", "1,2",
                ],
                "env": cpu,
                "budget": args.stage_budget or 900,
            },
            "exchange": {
                # Dense/delta frontier-exchange A/B at smoke shapes:
                # three legs (replicated, sharded/dense, sharded/delta)
                # must come back bitwise-equal, rows carry achieved
                # exchange words/tick (mesh_rehearsal pins the CPU
                # virtual mesh by design).
                "argv": [
                    py, os.path.join(SCRIPTS, "mesh_rehearsal.py"),
                    "--nodes", "2000", "--prob", "0.01", "--shares", "32",
                    "--horizon", "24", "--chunkSize", "32",
                    "--exchange", "ab", "--partition",
                ],
                "env": cpu,
                "budget": args.stage_budget or 900,
            },
            "exchange_hub": {
                # Degree-split hub/tail transport at smoke shapes: the
                # hub leg next to dense/delta, all bitwise-equal, with
                # a forced 16-row hub set (the small ER graph is too
                # flat for the modeled crossover to pick one).
                "argv": [
                    py, os.path.join(SCRIPTS, "mesh_rehearsal.py"),
                    "--nodes", "2000", "--prob", "0.01", "--shares", "32",
                    "--horizon", "24", "--chunkSize", "32",
                    "--exchange", "ab", "--hub-rows", "16", "--partition",
                ],
                "env": cpu,
                "budget": args.stage_budget or 900,
            },
            "serve": {
                # Continuous-batching server smoke: 12 mixed requests
                # drained on the 8-virtual-device slot mesh, every
                # request bitwise-compared against its solo campaign
                # run inside the script.
                "argv": [
                    py, os.path.join(SCRIPTS, "serve_bench.py"),
                    "--smoke",
                ],
                "env": cpu,
                "budget": args.stage_budget or 900,
            },
            "scale1m": {
                "argv": [
                    py, os.path.join(SCRIPTS, "scale_1m.py"),
                    "--nodes", "2000", "--prob", "0.01", "--shares", "64",
                    "--horizon", "32", "--block", "8",
                ],
                "env": cpu,
                "budget": args.stage_budget or 900,
            },
            "scale1m_full": {
                "argv": [
                    py, os.path.join(SCRIPTS, "scale_1m.py"),
                    "--nodes", "2000", "--prob", "0.01", "--shares", "128",
                    "--horizon", "32", "--block", "8",
                ],
                "env": cpu,
                "budget": args.stage_budget or 900,
            },
            "scale1m_ba": {
                "argv": [
                    py, os.path.join(SCRIPTS, "scale_1m.py"),
                    "--topology", "ba", "--nodes", "2000", "--baM", "3",
                    "--shares", "64", "--horizon", "48", "--block", "8",
                ],
                "env": cpu,
                "budget": args.stage_budget or 900,
            },
        }
    kb = [py, os.path.join(SCRIPTS, "kernel_bench.py")]
    # Bound every stage's device wait WELL inside its wall budget: the
    # battery only starts a stage after a healthy probe, so a long
    # in-stage wait means a fresh wedge and the budget should go to the
    # next health probe, not to waiting. Both knobs are set because
    # kernel_bench reads P2P_DEVICE_WAIT_S (no explicit budget) while
    # scale_1m's explicit long budget reads P2P_LONG_DEVICE_WAIT_S —
    # and both OVERRIDE any operator export for the child process.
    sweep_env = {
        "P2P_DEVICE_WAIT_S": "600",
        "P2P_LONG_DEVICE_WAIT_S": "600",
    }
    def bench_spec():
        return {
            "argv": [py, os.path.join(REPO, "bench.py")],
            # Bound the wait: the battery only starts a stage after a
            # healthy probe, so a long in-stage wait means a fresh wedge.
            "env": {"P2P_DEVICE_WAIT_S": "600"},
            "budget": args.stage_budget or 1800,
        }

    return {
        # One spec for the headline bench and its variance repeats (same
        # rationale as the smoke block).
        **{n: bench_spec() for n in ("bench", "bench_rep2", "bench_rep3")},
        "protocols": {
            "argv": [
                py, os.path.join(SCRIPTS, "protocol_compare.py"),
                "--nodes", "100000", "--prob", "0.001", "--shares", "64",
                "--horizon", "96", "--json",
            ],
            "env": sweep_env,
            "budget": args.stage_budget or 1800,
        },
        "kernel": {
            # 2700s: the gather section now also runs the word-width
            # sweep and the RCM-relabeled row (5 extra compile+measure
            # cycles plus a host-side RCM + restaging after the block
            # sweep).
            "argv": kb + ["--rows", "100000"],
            "env": sweep_env,
            "budget": args.stage_budget or 2700,
        },
        "sweep250": {
            # No --skip-gather here: the kernel stage (already banked)
            # ran the gather sweep before block 128 was added to
            # kernel_bench, so this stage carries the open question of
            # whether the round-1 block sweep stopped short of the
            # optimum. The gather runs at min(rows, 100K) = the bench
            # shape either way. 2700s: the gather section runs LAST in
            # kernel_bench (this stage once timed out at 1500s before
            # reaching it) and now also includes the word-width sweep
            # and the RCM-relabeled row — 5 extra compile+measure cycles
            # plus a host-side RCM + restaging.
            "argv": kb + ["--rows", "250000"],
            "env": sweep_env,
            "budget": args.stage_budget or 2700,
        },
        "campaign": {
            # The acceptance campaign spec (all four protocols at
            # R=32 x N=1024 with --compare-sequential): first hardware
            # validation of the vmapped campaign kernels and the packed
            # share pad, with the per-protocol sequential speedups as
            # stdout JSON lines. Standard XLA ops only.
            "argv": [
                py, os.path.join(SCRIPTS, "sweep.py"),
                "--sweep", os.path.join(REPO, "examples",
                                        "campaign_accept.json"),
                "--compare-sequential", "--no-report",
            ],
            "env": sweep_env,
            "budget": args.stage_budget or 1800,
        },
        "staticcheck": {
            # On-chip leg of the static-analysis gate: audit + sentinel
            # as on CPU, plus lower+compile of every registered entry on
            # the real chip. Compiles only — nothing executes at scale.
            "argv": [
                py, os.path.join(SCRIPTS, "staticcheck.py"),
                "--json", "--compile",
            ],
            "env": sweep_env,
            "budget": args.stage_budget or 1800,
        },
        "telemetry": {
            # First hardware execution of the ring-instrumented kernels:
            # a 20K-node flood with --telemetry through the real CLI,
            # stream schema-validated and ring metrics reconciled with
            # the final counters. Modest shape — far below the bench
            # config — because the job is validating instrumentation,
            # not measuring throughput.
            "argv": [
                py, os.path.join(SCRIPTS, "run_report.py"),
                "--capture-smoke", "--nodes", "20000", "--shares", "64",
            ],
            "env": sweep_env,
            "budget": args.stage_budget or 1200,
        },
        "flightrec": {
            # The flight recorder's hardware leg: all engine pairs with
            # digests ON, bisected (a clean chip must report zero
            # divergence — cross-engine bitwise parity ON HARDWARE),
            # plus the engine.sync compiled-cost ledger from the chip's
            # compiler. Tiny sims, standard XLA, compiles dominated by
            # the staticcheck stage's — safe band.
            "argv": [
                py, os.path.join(SCRIPTS, "divergence.py"), "--json",
                "--with-cost",
            ],
            "env": sweep_env,
            "budget": args.stage_budget or 1800,
        },
        "profile": {
            # One profiled bench pass + trace parse. --art-dir follows
            # the battery's artifact dir (default docs/artifacts) so a
            # redirected battery keeps its captures contained too.
            "argv": [
                py, os.path.join(SCRIPTS, "profile_capture.py"),
                "--art-dir", args.art_dir,
            ],
            "env": sweep_env,
            "budget": args.stage_budget or 1800,
        },
        "exchange": {
            # The dense/delta frontier-exchange crossover at rehearsal
            # scale: BA 100K on the 8-virtual-device host mesh, all
            # legs bitwise-checked, achieved exchange words/tick per
            # wire format in the rows. mesh_rehearsal pins
            # JAX_PLATFORMS=cpu by design (the delta exchange needs
            # >= 4 mesh devices; a single-chip tunnel has one) — the
            # rows are self-describing about that, so this stage is
            # mechanics + crossover evidence, not a chip perf number.
            # No --cache: the native BA build at 100K is seconds.
            "argv": [
                py, os.path.join(SCRIPTS, "mesh_rehearsal.py"),
                "--topology", "ba", "--nodes", "100000", "--baM", "3",
                "--shares", "64", "--horizon", "48", "--exchange", "ab",
                "--partition", "--skip-parity",
            ],
            "env": sweep_env,
            "budget": args.stage_budget or 3600,
        },
        "exchange_hub": {
            # The degree-split hub/tail transport at rehearsal scale:
            # BA 100K (a real scale-free degree profile, so the split
            # threshold comes from the modeled word-count crossover,
            # not a forced count) with dense + delta + hub legs plus
            # async-hub K in {2, 4} composition, all bitwise-checked
            # before any words/tick lands in a row. Host-mesh CPU by
            # design (PENDING_TPU_STAGES note): wire-format crossover
            # evidence, not a chip number; the record stays pending_tpu
            # until a real multi-chip mesh is attached.
            "argv": [
                py, os.path.join(SCRIPTS, "mesh_rehearsal.py"),
                "--topology", "ba", "--nodes", "100000", "--baM", "3",
                "--shares", "64", "--horizon", "48", "--exchange", "hub",
                "--async-k", "2,4", "--partition", "--skip-parity",
            ],
            "env": sweep_env,
            "budget": args.stage_budget or 3600,
        },
        "campaign_sharded": {
            # Campaigns x shards at the acceptance shape: R=4 replicas
            # of the node-sharded 100K BA graph as ONE compiled program
            # on the (2 replicas x 4 nodes) 8-virtual-device host mesh,
            # dense AND delta legs, each replica bitwise-checked against
            # its solo sharded run, warm/fresh walls vs the sequential
            # solo-sharded loop in the rows. mesh_rehearsal pins
            # JAX_PLATFORMS=cpu by design (PENDING_TPU_STAGES note) —
            # this is mechanics + throughput-factorization evidence, not
            # a chip perf number, and the record stays pending_tpu until
            # a real multi-chip mesh is attached.
            "argv": [
                py, os.path.join(SCRIPTS, "mesh_rehearsal.py"),
                "--topology", "ba", "--nodes", "100000", "--baM", "3",
                "--shares", "64", "--horizon", "48", "--replicas", "4",
                "--replica-shards", "2", "--exchange", "ab",
            ],
            "env": sweep_env,
            "budget": args.stage_budget or 3600,
        },
        "async_ticks": {
            # Bounded-staleness async ticks at the acceptance shape:
            # the 100K BA graph node-sharded 8 ways, sync dense + delta
            # legs next to async K in {1, 2, 4} on both transports
            # (mesh_rehearsal --async-k). The script asserts the parity
            # ladder before timing — K=1 bitwise-equal to the sync legs,
            # K>=2 equal at the fixed point — so the wall_per_tick_s and
            # modeled_overlap_fraction in each row are parity-certified.
            # Host-mesh CPU by design (PENDING_TPU_STAGES note): overlap
            # mechanics evidence, not a chip number; the record stays
            # pending_tpu until a real multi-chip mesh is attached.
            "argv": [
                py, os.path.join(SCRIPTS, "mesh_rehearsal.py"),
                "--topology", "ba", "--nodes", "100000", "--baM", "3",
                "--shares", "64", "--horizon", "48", "--exchange", "ab",
                "--async-k", "1,2,4", "--skip-parity",
            ],
            "env": sweep_env,
            "budget": args.stage_budget or 3600,
        },
        "serve": {
            # The serving acceptance trace: 100 mixed requests (2
            # topology fingerprints x 3 protocols x replica counts
            # cycling 1/2/4, plus a lossy-flood signature) through the
            # continuous-batching server, drained on the slot mesh, and
            # every request re-derived by a solo batch/campaign run and
            # compared bitwise before the row is accepted. serve_bench
            # pins the 8-virtual-device host CPU mesh when no platform
            # is requested (PENDING_TPU_STAGES note): serving-mechanics
            # + packing-throughput evidence, not a chip number; the
            # record stays pending_tpu until a real multi-chip mesh is
            # attached.
            "argv": [
                py, os.path.join(SCRIPTS, "serve_bench.py"),
                "--requests", "100",
            ],
            "env": sweep_env,
            "budget": args.stage_budget or 1800,
        },
        "scale1m": {
            # The minimal-footprint rung of the 1M ladder: --chunk 64
            # pins the pad to W=2, so resident memory is essentially the
            # bare staged ELL (~5.2 GB modeled) — the least the 1M graph
            # can occupy at all. Slow per gathered byte (sub-lane W) but
            # the job is 64 origins; what it buys is the first-ever 1M
            # on-chip completion at the lowest possible crash risk. The
            # auto-chunked ~8.8 GB shape is scale1m_full's job, ordered
            # behind every proven-safe stage.
            "argv": [
                py, os.path.join(SCRIPTS, "scale_1m.py"),
                "--shares", "64", "--chunk", "64",
                "--cache", args.cache, "--block", str(args.block),
            ],
            "env": sweep_env,
            "budget": args.stage_budget or 3600,
        },
        "scale1m_full": {
            "argv": [
                py, os.path.join(SCRIPTS, "scale_1m.py"),
                "--cache", args.cache, "--block", str(args.block),
            ],
            "env": sweep_env,
            "budget": args.stage_budget or 3600,
        },
        "scale1m_ba": {
            # BASELINE config 4: 1M-node scale-free. Mean degree ~2m is
            # far below the ER north star's ~1000, but the hub rows give
            # the degree-bucketed gather its worst-case skew. Pinned to
            # the minimal W=2 pad for the same reason as scale1m: the
            # W=128 crash suspect (N x W frontier/scratch buffers) is
            # topology-independent — even with BA's tiny ELL the default
            # pad models ~7.7 GB — and a worker crash here would wedge
            # every later stage.
            "argv": [
                py, os.path.join(SCRIPTS, "scale_1m.py"),
                "--topology", "ba", "--baM", "3", "--shares", "64",
                "--chunk", "64",
                "--cache", args.ba_cache, "--block", str(args.block),
            ],
            "env": sweep_env,
            "budget": args.stage_budget or 3600,
        },
    }


def latest_records(art_dir: str) -> dict[str, dict]:
    """Latest record per stage across every battery_*.jsonl artifact —
    the same latest-record-wins rule battery_report.py judges by. Smoke
    records prove the machinery, not the chip: they are ignored, or a
    bare `--smoke` run into the default art dir would let the watcher's
    next --skip-done fire skip every real stage on CPU evidence."""
    import glob

    latest: dict[str, dict] = {}
    for path in glob.glob(os.path.join(art_dir, "battery_*.jsonl")):
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            name = rec.get("stage")
            if not name or name.startswith("_") or rec.get("smoke"):
                continue
            if name not in latest or rec.get("utc", "") >= latest[name].get(
                "utc", ""
            ):
                latest[name] = rec
    return latest


def run_stage(name: str, spec: dict, hb_path: str | None = None) -> dict:
    """Run one stage to completion (or budget/crash) and return its
    record. stdout lines that parse as JSON are the stage's results.
    ``hb_path`` is the stage's heartbeat file (P2P_HEARTBEAT in its
    env): on a budget kill, the last beat rides the timeout record so
    the artifact says WHERE the stage was when it died — chunk index,
    ticks done, coverage — not just that it died."""
    t0 = time.monotonic()
    log(f"stage {name}: {' '.join(spec['argv'])} (budget {spec['budget']}s)")
    try:
        proc = subprocess.run(
            spec["argv"], timeout=spec["budget"], capture_output=True,
            text=True, env=stage_env(spec["env"]), cwd=REPO,
        )
        rc: int | str = proc.returncode
        out, err = proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        rc = "timeout"
        out = (e.stdout or b"").decode(errors="replace") if isinstance(
            e.stdout, bytes) else (e.stdout or "")
        err = (e.stderr or b"").decode(errors="replace") if isinstance(
            e.stderr, bytes) else (e.stderr or "")
    wall = time.monotonic() - t0
    results, raw = [], []
    for line in out.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            results.append(json.loads(line))
        except json.JSONDecodeError:
            raw.append(line)
    rec = {
        "stage": name,
        "argv": spec["argv"],
        "rc": rc,
        "ok": rc == 0,
        "wall_s": round(wall, 1),
        "results": results,
        "stdout_nonjson": raw[-5:],
        "stderr_tail": err[-1500:],
        "utc": datetime.now(timezone.utc).isoformat(timespec="seconds"),
    }
    if rc == "timeout" and hb_path:
        from p2p_gossip_tpu.telemetry import progress

        hb = progress.read_heartbeat(hb_path)
        age = progress.heartbeat_age_s(hb_path)
        if hb is not None:
            rec["heartbeat"] = hb
        if age is not None:
            rec["heartbeat_age_s"] = round(age, 1)
    log(f"stage {name}: rc={rc} wall={wall:.0f}s results={len(results)}")
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--stages", default=",".join(STAGE_ORDER),
        help=f"comma list from {STAGE_ORDER}, run in canonical order",
    )
    ap.add_argument(
        "--smoke", action="store_true",
        help="tiny CPU shapes: tests the battery machinery, not the chip",
    )
    ap.add_argument(
        "--stage-budget", type=int, default=0,
        help="override every stage's wall budget (seconds; 0 = defaults)",
    )
    ap.add_argument("--cache", default="/tmp/er1m.npz",
                    help="graph cache for the scale1m stage")
    ap.add_argument("--ba-cache", default="/tmp/ba1m.npz",
                    help="graph cache for the scale1m_ba stage")
    ap.add_argument("--block", type=int, default=8,
                    help="degree block for the scale1m/scale1m_ba stages")
    ap.add_argument(
        "--no-probe", action="store_true",
        help="skip inter-stage health probes (smoke/CPU runs)",
    )
    ap.add_argument(
        "--skip-done", action="store_true",
        help="skip stages whose latest artifact record is already ok — "
        "a re-fire (the tunnel watcher's mode) then only runs what a "
        "wedge skipped or failed, instead of burning the tunnel-up "
        "window repeating succeeded heavy stages",
    )
    ap.add_argument(
        "--art-dir", default=os.environ.get("P2P_BATTERY_DIR", ART_DIR),
        help="artifact directory (default docs/artifacts; real on-chip "
        "runs commit theirs, tests point this at a tmp dir)",
    )
    args = ap.parse_args()

    wanted = [s.strip() for s in args.stages.split(",") if s.strip()]
    unknown = [s for s in wanted if s not in STAGE_ORDER]
    if unknown:
        print(f"error: unknown stages {unknown}; valid: {STAGE_ORDER}",
              file=sys.stderr)
        return 2
    stages = [s for s in STAGE_ORDER if s in wanted]
    specs = stage_specs(args)
    probing = not (args.no_probe or args.smoke)

    os.makedirs(args.art_dir, exist_ok=True)
    # Every stage streams liveness to one heartbeat file in the artifact
    # dir: the chunk drivers rewrite it per chunk (telemetry/progress.py)
    # and tunnel_watch.py reads its age to tell a long stage from a
    # wedge. The battery itself reads it back on budget kills.
    hb_path = os.path.join(args.art_dir, "heartbeat.json")
    for spec in specs.values():
        spec["env"] = {**spec["env"], "P2P_HEARTBEAT": hb_path}
    stamp = datetime.now(timezone.utc).strftime("%Y%m%dT%H%M%SZ")
    art_path = os.path.join(args.art_dir, f"battery_{stamp}.jsonl")
    latest = os.path.join(args.art_dir, "battery_latest.jsonl")

    def persist(rec: dict) -> None:
        # Append + copy-to-latest after EVERY stage: a later worker crash
        # (or a kill of this process) keeps everything already measured.
        with open(art_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
        shutil.copyfile(art_path, latest)

    def abort_record(reason: str) -> dict:
        # Same schema as stage records so artifact consumers can iterate
        # uniformly — aborted batteries are exactly when the trail matters.
        return {
            "stage": "_abort", "argv": [], "rc": "abort", "ok": False,
            "wall_s": 0.0, "results": [], "stdout_nonjson": [],
            "stderr_tail": reason,
            "utc": datetime.now(timezone.utc).isoformat(timespec="seconds"),
        }

    summary = {"artifact": art_path, "stages": {}, "aborted": None,
               "skipped_done": [], "smoke": bool(args.smoke)}
    if args.skip_done:
        prior = latest_records(args.art_dir)
        done = {n for n, rec in prior.items() if rec.get("ok")}
        # pending_tpu rows (host-mesh stages recorded without a real
        # multi-chip mesh attached) stop counting as done the moment
        # the probe sees >= 4 real chips: the first multi-chip window
        # re-captures them on hardware. Probe only when it matters —
        # a pending record exists among the wanted stages.
        pending = {
            s for s in stages
            if s in done and prior[s].get("pending_tpu")
        }
        if pending and probing and multichip_attached():
            log(f"multi-chip mesh attached: re-running pending-TPU "
                f"stages {sorted(pending)}")
            done -= pending
        summary["skipped_done"] = [s for s in stages if s in done]
        for s in summary["skipped_done"]:
            # Counts as ok for the exit code: its evidence already
            # exists. Carry that evidence VERBATIM into this run's
            # artifact — persist() copies the artifact over
            # battery_latest.jsonl, so without the carry a re-fire that
            # ran one stage would leave a "latest" file missing the
            # other seven for battery_report.py.
            summary["stages"][s] = {"ok": True, "rc": "skipped-done"}
            persist(prior[s])
        stages = [s for s in stages if s not in done]
        if summary["skipped_done"]:
            log(f"skip-done: {summary['skipped_done']} already ok in "
                f"{args.art_dir}; running {stages or 'nothing'}")
        if not stages:
            print(json.dumps(summary))
            return 0

    if probing and not tunnel_healthy():
        summary["aborted"] = "tunnel unhealthy before first stage"
        persist(abort_record(summary["aborted"]))
        print(json.dumps(summary))
        return 1

    for i, name in enumerate(stages):
        rec = run_stage(name, specs[name], hb_path=hb_path)
        if args.smoke:
            # Mark so done_stages never counts CPU smoke runs as on-chip
            # evidence (and artifact readers can tell them apart).
            rec["smoke"] = True
        if name in PENDING_TPU_STAGES and not (
            probing and multichip_attached()
        ):
            # Host-mesh CPU evidence: a real multi-chip record is still
            # owed (see PENDING_TPU_STAGES) — --skip-done re-runs this
            # stage on the first window that attaches such a mesh.
            rec["pending_tpu"] = True
        persist(rec)
        summary["stages"][name] = {"ok": rec["ok"], "rc": rec["rc"]}
        remaining = stages[i + 1:]
        if remaining and probing:
            # A stage that just crashed the worker leaves the tunnel
            # wedged for ~1h; probing now (and aborting on failure) keeps
            # the already-persisted artifacts instead of queueing every
            # later stage behind a dead tunnel.
            if not tunnel_healthy():
                summary["aborted"] = (
                    f"tunnel unhealthy after stage {name}; "
                    f"skipped {remaining}"
                )
                log(summary["aborted"])
                persist(abort_record(summary["aborted"]))
                break
    print(json.dumps(summary))
    # Nonzero on abort OR any failed stage: automation watching this
    # exit code must not read "tunnel stayed healthy" as "evidence
    # captured" when every stage actually failed.
    all_ok = all(s["ok"] for s in summary["stages"].values())
    return 0 if summary["aborted"] is None and all_ok else 1


if __name__ == "__main__":
    sys.exit(main())
