"""Static-analysis gate for the compiled stack — one command, three analyzers.

    python scripts/staticcheck.py              # human report
    python scripts/staticcheck.py --json       # one JSON line on stdout
    python scripts/staticcheck.py --fixture f64|recompile|prng|
                                           telemetry|digest|exchange|
                                           meshfact|async
    python scripts/staticcheck.py --compile    # also lower+compile each
                                               # audited entry on the
                                               # default device (the
                                               # battery's on-chip stage)

Runs, in order: the AST lint (astlint — no jax needed), the jaxpr
invariant auditor over every registered entry point (jaxpr_audit), the
telemetry zero-cost check (telemetry_off — disabled metric rings must
compile away), and the recompile sentinel's replays (recompile): the
sweep-grid one and the serving scheduler's mixed request trace
(``run_serve_sentinel`` — one compile per distinct static signature
across backfilled slots). Exit code 1 iff
any analyzer reports a violation — which is also the ``--fixture``
contract: each seeded regression must keep exiting non-zero, and
tests/test_staticcheck.py asserts exactly that (a broken analyzer shows
up as the fixture exiting 0).

Wired into tier-1 by scripts/ci_tier1.sh (before pytest) and into
bench.py (the ``staticcheck_ok`` field). Diagnostics go to stderr;
stdout carries the report. Rule catalogue: docs/STATIC_ANALYSIS.md.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _setup_backend(need_jax: bool) -> None:
    """CPU-pinned runs get 8 virtual host devices (so the sharded audit
    specs stage a real 2x2 mesh) and the tunnel plugin deregistered —
    both must happen before the first jax device query."""
    if not need_jax:
        return
    from p2p_gossip_tpu.utils.platform import (
        cpu_requested,
        force_cpu_backend_if_requested,
    )

    if cpu_requested():
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    force_cpu_backend_if_requested()


def _compile_entries() -> dict:
    """Lower + compile every audited entry on the default device — the
    on-chip leg: an entry whose jaxpr audits clean can still fail XLA/
    Mosaic compilation on real hardware shapes. Returns per-entry
    status; never raises."""
    import jax

    from p2p_gossip_tpu.staticcheck import entrypoints, registry

    entrypoints.load_all()
    results, ok = [], True
    for entry in registry.all_entries():
        t0 = time.monotonic()
        try:
            spec = entry.spec()
            fn = spec.fn if spec.fn is not None else entry.fn
            jax.jit(
                lambda *args, _fn=fn, _kw=spec.kwargs: _fn(*args, **_kw)
            ).lower(*spec.args).compile()
            results.append({
                "entry": entry.name, "ok": True,
                "wall_s": round(time.monotonic() - t0, 2),
            })
        except Exception as e:
            ok = False
            results.append({
                "entry": entry.name, "ok": False,
                "error": f"{type(e).__name__}: {e}"[:500],
                "wall_s": round(time.monotonic() - t0, 2),
            })
    return {"ok": ok, "entries": results}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true",
                    help="one JSON line on stdout instead of the human report")
    ap.add_argument("--fixture",
                    choices=("f64", "recompile", "prng", "telemetry",
                             "digest", "exchange", "meshfact", "async",
                             "hub"),
                    help="run one seeded regression fixture; exits non-zero "
                    "iff the analyzer (correctly) flags it")
    ap.add_argument("--lint-only", action="store_true",
                    help="AST lint only — no jax tracing, ~2 s")
    ap.add_argument("--skip-sentinel", action="store_true",
                    help="skip the recompile sentinel's sweep replay")
    ap.add_argument("--compile", action="store_true",
                    help="additionally lower+compile each audited entry on "
                    "the default device (on-chip battery stage)")
    args = ap.parse_args()

    if args.fixture:
        _setup_backend(need_jax=args.fixture != "prng")
        from p2p_gossip_tpu.staticcheck.fixtures import run_fixture

        report = run_fixture(args.fixture)
        out = json.dumps(report) if args.json else "\n".join(
            [f"fixture {report['fixture']}: "
             + ("FLAGGED (expected)" if not report["ok"] else
                "NOT flagged — analyzer is blind to this regression")]
            + [f"  [{v.get('rule')}] {v.get('message')}"
               for v in report["violations"]]
        )
        print(out)
        return 0 if report["ok"] else 1

    _setup_backend(need_jax=not args.lint_only)
    report: dict = {}
    violations = 0
    t0 = time.monotonic()

    from p2p_gossip_tpu.staticcheck.astlint import run_lint

    lint = run_lint()
    report["lint"] = lint
    violations += len(lint["violations"])
    log(f"lint: {lint['files_scanned']} files, "
        f"{len(lint['violations'])} violation(s)")

    if not args.lint_only:
        if args.compile:
            # The compile leg may target the real chip: bounded wait with
            # the CPU fallback contract every on-chip script shares.
            from p2p_gossip_tpu.utils.platform import (
                cpu_requested,
                force_cpu_backend_if_requested,
                wait_for_device,
            )

            if not cpu_requested():
                try:
                    wait_for_device()
                except Exception as e:
                    log(f"staticcheck: device unreachable "
                        f"({type(e).__name__}); compiling on host CPU")
                    os.environ["JAX_PLATFORMS"] = "cpu"
                    force_cpu_backend_if_requested()

        from p2p_gossip_tpu.staticcheck.jaxpr_audit import run_audit

        audit = run_audit()
        report["jaxpr"] = audit
        violations += len(audit["violations"])
        log(f"jaxpr audit: {audit['entries_audited']} entries, "
            f"{len(audit['violations'])} violation(s)")

        from p2p_gossip_tpu.staticcheck.telemetry_off import (
            run_telemetry_check,
        )

        tel = run_telemetry_check()
        report["telemetry"] = tel
        violations += len(tel["violations"])
        log(f"telemetry zero-cost: {tel['pairs_checked']} instrumented "
            f"pair(s), {len(tel['violations'])} violation(s)")

        if not args.skip_sentinel:
            from p2p_gossip_tpu.staticcheck.recompile import run_sentinel

            sentinel = run_sentinel()
            report["recompile"] = {
                **sentinel.as_dict(),
                "violations": [
                    {"rule": "recompile-sentinel", "message": m}
                    for m in sentinel.violations()
                ],
            }
            violations += len(sentinel.violations())
            log(f"recompile sentinel: {sentinel.cells} cells, "
                f"expected {sentinel.expected}, measured {sentinel.measured}")

            from p2p_gossip_tpu.staticcheck.recompile import (
                run_serve_sentinel,
            )

            serve_sentinel = run_serve_sentinel()
            report["serve_recompile"] = {
                **serve_sentinel.as_dict(),
                "violations": [
                    {"rule": "serve-recompile-sentinel", "message": m}
                    for m in serve_sentinel.violations()
                ],
            }
            violations += len(serve_sentinel.violations())
            log(f"serve sentinel: {serve_sentinel.cells} requests, "
                f"expected {serve_sentinel.expected}, "
                f"measured {serve_sentinel.measured}")

        if args.compile:
            import jax

            comp = _compile_entries()
            report["compile"] = comp
            report["platform"] = jax.devices()[0].platform
            if not comp["ok"]:
                violations += sum(
                    1 for r in comp["entries"] if not r["ok"]
                )
            log(f"compile: {sum(r['ok'] for r in comp['entries'])}/"
                f"{len(comp['entries'])} entries compiled clean on "
                f"{report['platform']}")

    report["ok"] = violations == 0
    report["violations_total"] = violations
    report["wall_s"] = round(time.monotonic() - t0, 2)

    if args.json:
        print(json.dumps(report))
    else:
        print(f"staticcheck: {'OK' if report['ok'] else 'FAIL'} "
              f"({violations} violation(s), {report['wall_s']}s)")
        for section in ("lint", "jaxpr", "telemetry", "recompile",
                        "serve_recompile", "compile"):
            sec = report.get(section)
            if not sec:
                continue
            for v in sec.get("violations", []):
                loc = f"{v.get('file')}:{v.get('line')}: " if "file" in v \
                    else (f"{v['entry']}: " if "entry" in v else "")
                print(f"  {loc}[{v.get('rule')}] {v.get('message')}")
            if section == "compile":
                for r in sec.get("entries", []):
                    if not r["ok"]:
                        print(f"  {r['entry']}: [compile] {r['error']}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
