"""Serving benchmark — a mixed request trace through the gossip server.

Generates a synthetic trace (>= 2 topology fingerprints x >= 2
protocols x mixed replica counts), submits every request to one
`GossipServer` draining onto a factorized slot mesh
(`parallel.mesh.make_slot_mesh` over the host's 8 virtual CPU devices
by default, the real chips on TPU), and reports the serving headline:
**requests/s and p50/p99 turnaround under the mixed trace**, plus mean
slot occupancy.

Unless ``--no-verify``, every request's counters and coverage are then
re-derived by a solo ``batch/campaign`` run with the same seeds and
compared bitwise — the server's core contract (slot placement and batch
composition are semantically inert). A mismatch fails the run.

Emits exactly one JSON line on stdout (diagnostics on stderr); the
``serve`` legs of bench.py and the on-chip battery both parse it.
Usage: python scripts/serve_bench.py [--requests 100] [--slots 8]
       [--devices 8] [--smoke] [--single-device] [--no-verify]
       [--seed 0] [--cpu] [--out FILE]
"""

import argparse
import json
import os
import sys
import time

# Self-locate (PYTHONPATH must stay off the repo — scale_1m.py header).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def build_trace(requests: int, seed: int, smoke: bool) -> list[dict]:
    """Deterministic mixed trace: round-robin over (topology, protocol)
    scenario templates with replica counts cycling 1/2/4 and globally
    unique replica seeds (so every request is distinct work)."""
    n = 128 if smoke else 256
    topologies = [
        {"family": "erdos_renyi", "n": n, "p": 8.0 / n, "seed": 11},
        {"family": "watts_strogatz", "n": n, "k": 8, "beta": 0.1,
         "seed": 12},
    ]
    scenarios = []
    for topo in topologies:
        for proto in ("flood", "pushpull", "pushk"):
            scenarios.append({"topology": topo, "protocol": proto})
    # One lossy flood variant: a distinct static signature in the mix.
    scenarios.append({
        "topology": topologies[0], "protocol": "flood", "loss_prob": 0.05,
    })
    replica_cycle = (1, 2, 4)
    trace, next_seed = [], int(seed)
    for i in range(requests):
        sc = scenarios[i % len(scenarios)]
        reps = replica_cycle[i % len(replica_cycle)]
        trace.append({
            "request_id": f"req-{i:04d}",
            "shares": 4,
            "horizon": 16 if smoke else 24,
            "seeds": list(range(next_seed, next_seed + reps)),
            **sc,
        })
        next_seed += reps
    return trace


def verify_request(server, request_dict) -> bool:
    """Bitwise-compare the server's result against a solo
    batch/campaign run of the same scenario + seeds (values, not
    dtypes: the sharded path accumulates int64 coverage)."""
    import numpy as np

    from p2p_gossip_tpu.batch.campaign import (
        flood_replicas,
        run_coverage_campaign,
        run_protocol_campaign,
    )
    from p2p_gossip_tpu.models.linkloss import LinkLossModel
    from p2p_gossip_tpu.models.seeds import replica_loss_seeds
    from p2p_gossip_tpu.serve.request import SimRequest

    req = SimRequest.from_dict(request_dict)
    got = server.result(req.request_id)
    graph = server._graph(req)
    replicas = flood_replicas(
        graph, req.shares, list(req.seeds), req.horizon,
        churn_prob=req.churn_prob, mean_down_ticks=req.mean_down_ticks,
        max_outages=req.max_outages,
    )
    loss = LinkLossModel(req.loss_prob) if req.loss_prob > 0 else None
    lseeds = replica_loss_seeds(list(req.seeds)) if loss else None
    if req.protocol == "flood":
        ref = run_coverage_campaign(
            graph, replicas, req.horizon, loss=loss, loss_seeds=lseeds,
        )
    else:
        ref = run_protocol_campaign(
            graph, replicas, req.horizon, protocol=req.protocol,
            fanout=req.fanout, record_coverage=True, loss=loss,
            loss_seeds=lseeds,
        )
    return all(
        np.array_equal(
            np.asarray(getattr(got, f)), np.asarray(getattr(ref, f))
        )
        for f in ("generated", "received", "sent", "coverage")
    )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=100)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--devices", type=int, default=8,
                    help="virtual host device fan-out on CPU")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized trace: 12 requests, smaller graphs")
    ap.add_argument("--single-device", action="store_true",
                    help="skip the slot mesh; dispatch on one device")
    ap.add_argument("--no-verify", action="store_true",
                    help="skip the per-request solo bitwise comparison")
    ap.add_argument("--out", help="also append the JSON row to FILE")
    from p2p_gossip_tpu.utils.platform import add_cpu_arg

    add_cpu_arg(ap)
    args = ap.parse_args()
    if args.smoke:
        args.requests = min(args.requests, 12)

    from p2p_gossip_tpu.utils.platform import apply_cpu_arg, cpu_requested

    apply_cpu_arg(args)
    if cpu_requested() or not os.environ.get("JAX_PLATFORMS"):
        # Host run: pin CPU and fan out virtual devices for the slot
        # mesh BEFORE jax loads (mesh_rehearsal.py's pattern).
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} "
                f"--xla_force_host_platform_device_count={args.devices}"
            ).strip()
    from p2p_gossip_tpu.utils.platform import force_cpu_backend_if_requested

    force_cpu_backend_if_requested()

    import jax
    import numpy as np

    from p2p_gossip_tpu.parallel.mesh import make_slot_mesh
    from p2p_gossip_tpu.serve.server import GossipServer

    platform = jax.devices()[0].platform
    mesh = None
    mesh_shape = "1x1"
    if not args.single_device:
        mesh = make_slot_mesh(args.slots)
        shape = dict(zip(mesh.axis_names, mesh.devices.shape))
        mesh_shape = f"{shape['replicas']}x{shape['nodes']}"
    log(f"serve_bench: {args.requests} requests, slots={args.slots}, "
        f"mesh={mesh_shape} on {platform}")

    trace = build_trace(args.requests, args.seed, args.smoke)
    server = GossipServer(slots=args.slots, mesh=mesh)

    t0 = time.perf_counter()
    for request_dict in trace:
        server.submit(request_dict)
    batches = server.drain()
    wall = time.perf_counter() - t0

    turnarounds = []
    for request_dict in trace:
        state = server._states[request_dict["request_id"]]
        if state.status != "done":
            log(f"serve_bench: request {request_dict['request_id']} "
                f"ended {state.status}")
            return 1
        turnarounds.append(state.turnaround_s)
    signatures = len({
        s.request.signature_key() for s in server._states.values()
    })
    log(f"serve_bench: drained {batches} batches "
        f"({signatures} signatures) in {wall:.2f}s, "
        f"occupancy {server.slot_occupancy():.3f}")

    bitwise_ok = None
    verified = 0
    if not args.no_verify:
        bitwise_ok = True
        for request_dict in trace:
            ok = verify_request(server, request_dict)
            verified += 1
            if not ok:
                bitwise_ok = False
                log(f"serve_bench: BITWISE MISMATCH on "
                    f"{request_dict['request_id']}")
        log(f"serve_bench: verified {verified}/{len(trace)} requests "
            f"vs solo campaign runs: "
            f"{'bitwise OK' if bitwise_ok else 'MISMATCH'}")

    row = {
        "bench": "serve",
        "platform": platform,
        "smoke": bool(args.smoke),
        "requests": len(trace),
        "signatures": signatures,
        "slots": args.slots,
        "mesh": mesh_shape,
        "batches": batches,
        "wall_s": round(wall, 3),
        "requests_per_s": round(len(trace) / wall, 3),
        "p50_turnaround_s": round(float(np.percentile(turnarounds, 50)), 4),
        "p99_turnaround_s": round(float(np.percentile(turnarounds, 99)), 4),
        "slot_occupancy": round(server.slot_occupancy(), 4),
        "verified": verified,
        "bitwise_ok": bitwise_ok,
    }
    line = json.dumps(row)
    print(line)
    if args.out:
        with open(args.out, "a") as f:
            f.write(line + "\n")
    return 0 if bitwise_ok in (True, None) else 1


if __name__ == "__main__":
    sys.exit(main())
