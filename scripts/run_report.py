"""Render a telemetry stream into a human run report.

    python scripts/run_report.py RUN.jsonl              # human report
    python scripts/run_report.py RUN.jsonl --json       # summary JSON line
    python scripts/run_report.py RUN.jsonl --validate   # schema gate (rc 1)
    python scripts/run_report.py RUN.jsonl --chrome OUT.json  # Perfetto
    python scripts/run_report.py --capture-smoke        # run a tiny flood
                                                        # with --telemetry,
                                                        # validate + report

Sections: run metadata, the span waterfall (host phases, nested by
depth), total span time by phase, one block per harvested metric ring
(per-tick frontier curve, messages/tick, loss drops), the flight
recorder's digest streams and progress beats, the compiled-cost ledger
(``cost.*`` counters from scripts/cost_report.py), and the jit-cache
counter samples (the PR-3 recompile-sentinel counters). Every section
is optional — a spans-only stream (bench keeps device rings off)
renders just the waterfall; a ring whose metric hits the uint32
saturation sentinel (4294967295) gets a wrap warning instead of a
silently-absurd total. The schema is
`p2p_gossip_tpu/telemetry/schema.py`; ``--chrome`` output opens in
chrome://tracing or https://ui.perfetto.dev (docs/OBSERVABILITY.md).

``--capture-smoke`` is the ci_tier1 / on-chip-battery entry point: it
runs a small flood-coverage simulation through the real CLI with
``--telemetry``, validates the emitted JSONL against the schema, checks
the ring's tick sums against the run's final counters, round-trips the
Chrome export, and prints one summary JSON line (``telemetry_smoke``).
Exit 0 iff every check passed.
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from p2p_gossip_tpu.telemetry import chrometrace, schema  # noqa: E402

SPARK = "▁▂▃▄▅▆▇█"
U32_MAX = 0xFFFFFFFF  # rings.u32sum saturation sentinel


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def sparkline(series) -> str:
    if not series:
        return ""
    peak = max(series) or 1
    return "".join(SPARK[min(len(SPARK) - 1, v * len(SPARK) // (peak + 1))]
                   for v in series)


def summarize(events) -> dict:
    """Aggregate a stream into the summary dict the JSON mode prints
    (and --capture-smoke embeds)."""
    spans = [e for e in events if e.get("type") == "span"]
    rings = [e for e in events if e.get("type") == "ring"]
    counters = [e for e in events if e.get("type") == "counter"]
    digests = [e for e in events if e.get("type") == "digest"]
    progress = [e for e in events if e.get("type") == "progress"]
    meta = next((e for e in events if e.get("type") == "meta"), None)
    span_s: dict = {}
    for s in spans:
        span_s[s["name"]] = round(span_s.get(s["name"], 0.0) + s["dur"], 4)
    ring_totals: dict = {}
    wrap_warnings: list[str] = []
    for r in rings:
        agg = ring_totals.setdefault(
            r["kernel"], {c: 0 for c in schema.METRIC_COLUMNS} | {"rings": 0}
        )
        agg["rings"] += 1
        for col in schema.METRIC_COLUMNS:
            series = r.get("metrics", {}).get(col, [])
            agg[col] += sum(series)
            # rings.u32sum saturates at the uint32 max instead of
            # wrapping — a tick stuck at the sentinel means the real
            # figure is LARGER and every total containing it is a floor.
            if any(v == U32_MAX for v in series):
                wrap_warnings.append(
                    f"{r['kernel']}.{col}: tick value saturated at "
                    f"2^32-1 (t0={r.get('t0', 0)}) — totals are lower "
                    "bounds"
                )
    digest_streams = sorted({
        (d.get("kernel"), d.get("chunk"), d.get("replica"), d.get("shard"))
        for d in digests
    }, key=str)
    return {
        "events": len(events),
        "spans": len(spans),
        "rings": len(rings),
        "digests": len(digests),
        "digest_streams": len(digest_streams),
        "progress": len(progress),
        "counters": {c["name"]: c["value"] for c in counters},
        "span_s_by_phase": span_s,
        "ring_totals": ring_totals,
        "wrap_warnings": wrap_warnings,
        "run": (meta or {}).get("run", {}),
    }


def render(events, out=sys.stdout) -> None:
    summary = summarize(events)
    w = out.write
    run = summary["run"]
    w("=== Telemetry run report ===\n")
    if run:
        w(f"run: {run.get('utc', '?')}  pid {run.get('pid', '?')}\n")
        if run.get("argv"):
            w(f"argv: {' '.join(run['argv'])}\n")
    spans = sorted(
        (e for e in events if e.get("type") == "span"),
        key=lambda s: s["ts"],
    )
    if spans:
        w("\n--- span waterfall (host phases) ---\n")
        for s in spans:
            attrs = s.get("attrs", {})
            label = ", ".join(f"{k}={v}" for k, v in attrs.items())
            w(
                f"{s['ts']:9.3f}s  {'  ' * s.get('depth', 0)}{s['name']}"
                f"  {s['dur'] * 1e3:9.2f} ms"
                + (f"  ({label})" if label else "")
                + "\n"
            )
        w("\n--- total span time by phase ---\n")
        for name, total in sorted(
            summary["span_s_by_phase"].items(), key=lambda kv: -kv[1]
        ):
            w(f"  {name:24s} {total * 1e3:10.2f} ms\n")
    rings = [e for e in events if e.get("type") == "ring"]
    if rings:
        w("\n--- device metric rings (per-tick, harvested per chunk) ---\n")
        for r in rings:
            prov = ", ".join(
                f"{k}={r[k]}" for k in ("chunk", "replica", "seed", "shard")
                if k in r
            )
            w(f"{r['kernel']}" + (f" [{prov}]" if prov else "")
              + f": {r['ticks']} tick(s) from t={r['t0']}\n")
            m = r.get("metrics", {})
            frontier = m.get("frontier_bits", [])
            if frontier:
                peak_t = max(range(len(frontier)), key=frontier.__getitem__)
                w(f"  frontier/tick: {sparkline(frontier)} "
                  f"(peak {frontier[peak_t]} @ t={r['t0'] + peak_t})\n")
            for col in schema.METRIC_COLUMNS:
                series = m.get(col, [])
                total = sum(series)
                mean = total / max(len(series), 1)
                sat = "  [SATURATED]" if any(
                    v == U32_MAX for v in series
                ) else ""
                w(f"  {col:15s} total {total:>12}  mean/tick {mean:>10.1f}"
                  f"  max {max(series) if series else 0:>10}{sat}\n")
    if summary["wrap_warnings"]:
        w("\n--- WARNING: uint32 metric saturation ---\n")
        for msg in summary["wrap_warnings"]:
            w(f"  {msg}\n")
    digests = [e for e in events if e.get("type") == "digest"]
    if digests:
        w("\n--- flight recorder: per-tick state digests ---\n")
        for d in digests:
            prov = ", ".join(
                f"{k}={d[k]}" for k in ("chunk", "replica", "seed", "shard")
                if k in d
            )
            values = d.get("values", [])
            head = f"{values[0]:08x}" if values else "-"
            tail = f"{values[-1]:08x}" if values else "-"
            w(f"{d['kernel']}" + (f" [{prov}]" if prov else "")
              + f": {d.get('ticks', len(values))} tick(s) from "
              f"t={d.get('t0', 0)}  digest {head}..{tail}\n")
        w("  (compare streams across engines: scripts/divergence.py)\n")
    progress = [e for e in events if e.get("type") == "progress"]
    if progress:
        w("\n--- progress beats (per-chunk liveness) ---\n")
        for p in progress:
            parts = [f"{p.get('elapsed_s', 0.0):8.3f}s",
                     p.get("kernel", "?")]
            if "chunk" in p:
                total = p.get("chunks_total")
                parts.append(f"chunk {p['chunk']}"
                             + (f"/{total}" if total is not None else ""))
            if "ticks_done" in p:
                parts.append(f"{p['ticks_done']} ticks")
            if "coverage_pct" in p:
                parts.append(f"{p['coverage_pct']:.1f}% coverage")
            if "digest_head" in p:
                parts.append(f"digest {p['digest_head']}")
            w("  " + "  ".join(str(x) for x in parts) + "\n")
    counters = [e for e in events if e.get("type") == "counter"]
    cost = [c for c in counters if c["name"].startswith("cost.")]
    other = [c for c in counters if not c["name"].startswith("cost.")]
    if cost:
        w("\n--- compiled-cost ledger (scripts/cost_report.py) ---\n")
        by_entry: dict = {}
        for c in cost:
            entry, _, field = c["name"][len("cost."):].rpartition(".")
            by_entry.setdefault(entry, {})[field] = c["value"]
        for entry, fields in sorted(by_entry.items()):
            w(f"  {entry}\n")
            for field, val in sorted(fields.items()):
                w(f"    {field:16s} {val}\n")
    if other:
        w("\n--- counters (jit-cache sentinel samples) ---\n")
        for c in other:
            w(f"  {c['name']:48s} {c['value']}\n")
    if not (spans or rings or digests or progress or counters):
        w("\n(no span/ring/digest/progress/counter events — empty or "
          "metadata-only stream)\n")


def _capture_smoke(args) -> int:
    """Run a tiny flood through the real CLI with --telemetry and gate
    the whole pipeline: JSONL schema, ring-vs-counter consistency, and
    the Chrome-trace round trip. One summary JSON line on stdout."""
    from p2p_gossip_tpu.utils.cli import run as cli_run

    result: dict = {"kind": "telemetry_smoke", "ok": False}
    with tempfile.TemporaryDirectory(prefix="p2p_tel_smoke_") as tmp:
        stream = os.path.join(tmp, "telemetry.jsonl")
        argv = [
            "--numNodes", str(args.nodes),
            "--connectionProb", "0.05",
            "--simTime", "0.25",
            "--Latency", "5",
            "--floodCoverage", str(args.shares),
            "--seed", "0",
            "--telemetry", stream,
            "--json",
        ]
        result["argv"] = argv
        import contextlib
        import io

        stdout = io.StringIO()
        with contextlib.redirect_stdout(stdout):
            rc = cli_run(argv)
        cli_json = None
        for line in stdout.getvalue().splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    cli_json = json.loads(line)
                except json.JSONDecodeError:
                    pass
        result["cli_rc"] = rc
        errors: list[str] = []
        if rc != 0:
            errors.append(f"CLI exited {rc}")
        if not os.path.exists(stream):
            errors.append("no telemetry stream written")
        else:
            with open(stream, encoding="utf-8") as f:
                lines = f.readlines()
            errors.extend(schema.validate_stream(lines))
            events = chrometrace.load_stream(stream)
            summary = summarize(events)
            result["summary"] = summary
            if not summary["rings"]:
                errors.append("no ring events in the stream")
            if not summary["spans"]:
                errors.append("no span events in the stream")
            # Per-tick metrics must reconcile with the run's counters:
            # summed newly_infected across rings == total received.
            newly = sum(
                agg["newly_infected"]
                for agg in summary["ring_totals"].values()
            )
            if cli_json is not None:
                # The flood-coverage CLI JSON has no received total;
                # derive it from the final coverage curve instead:
                # sum(final coverage) - shares = receives (each origin
                # already held its own share).
                fc = cli_json.get("final_coverage", {})
                expect = None
                if fc and "mean" in fc:
                    expect = int(round(fc["mean"] * args.shares)) - args.shares
                result["newly_infected_total"] = newly
                result["expected_receives"] = expect
                if expect is not None and newly != expect:
                    errors.append(
                        f"ring newly_infected {newly} != expected "
                        f"receives {expect}"
                    )
            # Chrome round trip.
            trace = chrometrace.to_chrome_trace(events)
            back = chrometrace.spans_from_chrome(trace)
            if len(back) != summary["spans"]:
                errors.append(
                    f"chrome round-trip lost spans "
                    f"({len(back)} != {summary['spans']})"
                )
        result["errors"] = errors
        result["ok"] = not errors
    print(json.dumps(result))
    return 0 if result["ok"] else 1


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("stream", nargs="?", help="telemetry JSONL file")
    ap.add_argument("--json", action="store_true",
                    help="one summary JSON line instead of the report")
    ap.add_argument("--validate", action="store_true",
                    help="schema-validate the stream; exit 1 on any error")
    ap.add_argument("--chrome", metavar="OUT.json", default="",
                    help="also export a Chrome-trace file (Perfetto)")
    ap.add_argument("--capture-smoke", action="store_true",
                    help="run a tiny flood with --telemetry, validate the "
                    "stream end to end (ci_tier1 / battery stage)")
    ap.add_argument("--nodes", type=int, default=96,
                    help="capture-smoke graph size")
    ap.add_argument("--shares", type=int, default=4,
                    help="capture-smoke flooded shares")
    args = ap.parse_args()

    if args.capture_smoke:
        return _capture_smoke(args)
    if not args.stream:
        ap.error("pass a telemetry JSONL file (or --capture-smoke)")
    if not os.path.exists(args.stream):
        log(f"error: {args.stream} not found")
        return 2

    if args.validate:
        with open(args.stream, encoding="utf-8") as f:
            errors = schema.validate_stream(f)
        if errors:
            for e in errors:
                log(f"schema: {e}")
            print(json.dumps({"ok": False, "errors": errors}))
            return 1
        print(json.dumps({"ok": True, "errors": []}))
        return 0

    events = chrometrace.load_stream(args.stream)
    if args.chrome:
        chrometrace.write_chrome_trace(events, args.chrome)
        log(f"chrome trace written to {args.chrome} "
            "(open in chrome://tracing or ui.perfetto.dev)")
    if args.json:
        print(json.dumps(summarize(events)))
    else:
        render(events)
    return 0


if __name__ == "__main__":
    sys.exit(main())
