"""Protocol comparison experiment: flood vs push-pull vs fanout push.

Runs the three protocols on the SAME graph and origins, and reports the
coverage/bandwidth trade-off each one makes — the experiment the
protocol family exists to support:

- flood (the reference's protocol, p2pnode.cc:127): fastest spread, one
  send per peer per processed share (~mean-degree sends per delivery);
- push-pull anti-entropy: guaranteed convergence, digest traffic every
  round whether or not anything is new;
- fanout push (rumor mongering): ~fanout sends per delivery, probabilistic
  coverage.

Usage: python scripts/protocol_compare.py [--nodes 2000] [--prob 0.005]
       [--shares 32] [--horizon 64] [--fanout 3] [--seed 0] [--json]

Prints a table (or one JSON line with --json); runs on the default JAX
device (set JAX_PLATFORMS=cpu to force CPU).
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=2000)
    ap.add_argument("--prob", type=float, default=0.005)
    ap.add_argument("--shares", type=int, default=32)
    ap.add_argument("--horizon", type=int, default=64)
    ap.add_argument("--fanout", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--coverageFraction", type=float, default=0.99)
    ap.add_argument("--json", action="store_true")
    from p2p_gossip_tpu.utils.platform import (
        add_cpu_arg,
        apply_cpu_arg,
        long_device_wait_s,
        wait_for_device,
    )

    add_cpu_arg(ap)
    args = ap.parse_args()
    apply_cpu_arg(args)

    # CPU: deregisters the tunnel plugin. TPU: waits out a wedged tunnel
    # with killable probes instead of hanging on first device query. No
    # CPU fallback here, so use the long-wait budget (bound per-run with
    # P2P_LONG_DEVICE_WAIT_S; P2P_DEVICE_WAIT_S can only raise it).
    wait_for_device(max_wait_s=long_device_wait_s())

    import p2p_gossip_tpu as pg
    from p2p_gossip_tpu.engine.sync import run_flood_coverage
    from p2p_gossip_tpu.models.generation import Schedule
    from p2p_gossip_tpu.models.protocols import run_pushk_sim, run_pushpull_sim
    from p2p_gossip_tpu.utils.analysis import (
        message_redundancy,
        propagation_latency,
    )

    g = pg.erdos_renyi(args.nodes, args.prob, seed=args.seed)
    rng = np.random.default_rng(args.seed)
    origins = rng.integers(0, g.n, args.shares).astype(np.int32)
    sched = Schedule(g.n, origins, np.zeros(args.shares, dtype=np.int32))
    frac = args.coverageFraction

    def measure(name, run):
        t0 = time.perf_counter()
        stats, cov = run()
        wall = time.perf_counter() - t0
        red = message_redundancy(stats)
        # All shares generate at t=0, so latency-to-coverage IS
        # time-to-coverage — one computation serves both report fields.
        s = propagation_latency(cov, g.n, fractions=(frac,)).summary(frac)
        return {
            "protocol": name,
            "reached_fraction": s["reached"],
            "ttc_median_ticks": s["median"],
            "final_coverage_mean": float(cov[-1].mean()),
            "sends_per_delivery": (
                None
                if red["sends_per_delivery"] is None
                else round(red["sends_per_delivery"], 2)
            ),
            "total_sent": int(stats.sent.sum()),
            "p95_latency_ticks": s["p95"],
            "wall_s": round(wall, 3),
        }

    rows = [
        measure(
            "flood",
            lambda: run_flood_coverage(g, origins, args.horizon),
        ),
        measure(
            "pushpull",
            lambda: run_pushpull_sim(
                g, sched, args.horizon, seed=args.seed, record_coverage=True
            ),
        ),
        measure(
            "pull",
            lambda: run_pushpull_sim(
                g, sched, args.horizon, seed=args.seed, record_coverage=True,
                mode="pull",
            ),
        ),
        measure(
            f"pushk(k={args.fanout})",
            lambda: run_pushk_sim(
                g, sched, args.horizon, fanout=args.fanout, seed=args.seed,
                record_coverage=True,
            ),
        ),
    ]

    if args.json:
        print(json.dumps({"config": vars(args), "results": rows}))
        return
    cols = list(rows[0].keys())
    widths = [max(len(c), *(len(str(r[c])) for r in rows)) for c in cols]
    print(
        f"N={g.n} edges={g.num_edges} shares={args.shares} "
        f"horizon={args.horizon} target={frac:.0%}"
    )
    print("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
    for r in rows:
        print("  ".join(str(r[c]).ljust(w) for c, w in zip(cols, widths)))


if __name__ == "__main__":
    main()
